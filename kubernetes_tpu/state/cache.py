"""Scheduler cache: authoritative in-memory cluster state with the
assumed-pod state machine and generation-tracked incremental tensor sync.

Reference: pkg/scheduler/internal/cache/cache.go. State machine for a pod
(interface.go:33-47):

    Initial → Assume → [bind succeeds] → Added (expires after TTL unless
    confirmed by the informer) → Update/Remove via informer events
    Assume → Forget (bind failed) → Initial

The cache is never authoritative storage — etcd is (SURVEY.md §5
checkpoint/resume): on restart everything is rebuilt from a fresh list+watch.
Device tensors are a further derived layer: `TensorMirror` keeps NodeBank /
SigBank (pod label signatures + per-node counts) in sync with this cache,
patching only DIRTY rows per
cycle the way UpdateNodeInfoSnapshot walks its generation-ordered dirty list
(cache.go:206-242).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from ..analysis.lockorder import audited_rlock
from ..api.types import Node, Pod
from ..oracle.nodeinfo import NodeInfo, Snapshot, pod_has_affinity_constraints
from .tensors import (
    ImageTable,
    KeySlotOverflow,
    NodeBank,
    SigBank,
    SigOverflow,
    Vocab,
    _bucket,
    _node_bucket,
)
from .terms import PatternBank, PatternOverflow

DEFAULT_ASSUME_TTL = 30.0  # cache.go durationToExpireAssumedPod (30s default)

_ROW_SCATTER = None
_ROW_SCATTER_DONATED = None

# dirty-row scatter row-count rungs: every (structure, rung) pair is one
# XLA program, so the rung set must be SMALL enough to pre-compile at
# warmup (TensorMirror.warm_patches) — a pow-2 ladder up to the batch
# bucket was one inline compile per fresh bucket, and those landed
# MID-DRAIN (the round-5 preemption config's cycle-2 "solve" spike was
# these scatters compiling after victim deletions dirtied rows). Bigger
# patches chunk at the top rung: same bytes, same programs.
PATCH_RUNGS = (16, 64, 256)


def _patch_rung(n: int) -> int:
    for r in PATCH_RUNGS:
        if n <= r:
            return r
    return PATCH_RUNGS[-1]


#: bytes_shipped kinds whose payloads are PREDOMINANTLY node-major bank
#: slices — on a mesh each shard receives 1/shards of them. Everything
#: else (fold control arrays) replicates to every shard in full.
#: Approximate by design: "full"/"rows" also carry the banks' [S]/[PT]-
#: major metadata arrays (replicated), counted here at 1/shards — they
#: are small next to the [N, *] matrices, and exact per-kind sub-
#: accounting would fork the user-facing metric label set.
NODE_MAJOR_SHIP_KINDS = frozenset({"full", "rows", "usage", "warm"})


def per_shard_bytes(shipped: Dict[str, int], shards: int) -> Dict[str, int]:
    """The per-shard view of a TensorMirror.bytes_shipped ledger: the one
    split policy bench.py and the multichip dryrun both report (see
    NODE_MAJOR_SHIP_KINDS for the approximation it makes)."""
    if not shards:
        return dict(shipped)
    return {
        k: (v // shards if k in NODE_MAJOR_SHIP_KINDS else v)
        for k, v in shipped.items()
    }


# ktpu: admitted(KIND_PATCH) dispatched only via TensorMirror._scatter_rows,
# which admits each (rung, structure) pair as a KIND_PATCH spec; warmed by
# TensorMirror.warm_patches at startup
def _row_scatter_fn():
    """One jitted row-scatter over a whole bank dict: a single dispatch
    updates every array's dirty rows (compiled once per (row-bucket,
    structure) pair)."""
    global _ROW_SCATTER
    if _ROW_SCATTER is None:
        import jax

        @jax.jit
        def scatter(dev, idx, updates):
            out = dict(dev)
            for k, u in updates.items():
                out[k] = dev[k].at[idx].set(u)
            return out

        _ROW_SCATTER = scatter
    return _ROW_SCATTER


# ktpu: admitted(KIND_PATCH) same spec family as _row_scatter_fn (the
# donated twin shares rungs/structure; donation is not part of the spec key)
def _row_scatter_donated_fn():
    """The same row-scatter with the resident bank DONATED: updated arrays
    scatter in place and untouched arrays alias straight through — the
    tens-of-MB banks stop being copied per patch. Only used when the
    driver enables it (TensorMirror.donate_patches): donation deletes the
    caller's input arrays, so every other holder of the bank dicts (e.g.
    warmup snapshots) must have been cut over to synthetic banks first."""
    global _ROW_SCATTER_DONATED
    if _ROW_SCATTER_DONATED is None:
        import jax
        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def scatter(dev, idx, updates):
            out = dict(dev)
            for k, u in updates.items():
                out[k] = dev[k].at[idx].set(u)
            return out

        _ROW_SCATTER_DONATED = scatter
    return _ROW_SCATTER_DONATED


@dataclass
class _PodState:
    pod: Pod
    assumed: bool = False
    binding_finished: bool = False
    deadline: Optional[float] = None  # TTL expiry for assumed pods


class SchedulerCache:
    """cache.go schedulerCache: node name → NodeInfo, pod key → state."""

    def __init__(self, ttl: float = DEFAULT_ASSUME_TTL, now: Callable[[], float] = time.monotonic):
        self._lock = audited_rlock("cache")
        self._ttl = ttl
        self._now = now
        self.snapshot = Snapshot()
        self._pod_states: Dict[str, _PodState] = {}  # ktpu: guarded-by(self._lock)
        self._assumed: Set[str] = set()
        # columnar plane (state/columns.py): attached by the driver under
        # KTPU_COLUMNAR_CACHE — bulk assume/forget become vectorized
        # column scatters and the NodeInfo objects a lazy journal-backed
        # view. None = every legacy path intact (the kill switch).
        self._columns = None  # ktpu: guarded-by(self._lock)
        # fault plane (kubernetes_tpu/faults): a broken columnar scatter
        # detaches the columns INLINE (object truth survives via the
        # journal) and reports here; None = one attribute read
        self.fault_sink = None
        self._deadlines = None  # ktpu: guarded-by(self._lock)
        self.dirty_nodes: Set[str] = set()  # generation-equivalent dirty set
        self.removed_nodes: Set[str] = set()
        # bumped on every snapshot mutation — the driver's speculative
        # pipeline uses it to detect state changes it did not account for
        self.mutation_count = 0  # ktpu: guarded-by(self._lock)
        # (node, pod, ±1, folded) single-pod changes (assume/confirm/
        # remove) — the overwhelmingly common event; consumed by
        # TensorMirror.sync. `folded` marks adds whose usage/count deltas
        # were ALREADY applied to the resident device banks by a commit
        # fold (ops/fold) — sync applies them to the host arrays exactly
        # the same, but records their rows as device-folded so
        # device_arrays() does not re-ship what the device already has.
        self.pod_deltas: List[Tuple[str, Pod, int, bool]] = []
        # zone-interleaved iteration (internal/cache/node_tree.go) for the
        # host-side placement loops' tie distribution
        from .node_tree import NodeTree

        self.node_tree = NodeTree()

    # -- columnar plane (state/columns.py) -----------------------------------

    def attach_columns(self, vocab):
        """Arm the columnar cache: hot columns adopt the current state,
        `snapshot.node_infos` becomes a lazy view resolved through the
        columns' journal, and assumed-pod TTLs move to a deadline
        column. Idempotent; called once by the driver (the
        KTPU_COLUMNAR_CACHE kill switch simply skips the call)."""
        from .columns import AssumedDeadlines, CacheColumns, LazyNodeInfos

        with self._lock:
            if self._columns is not None:
                if self._columns.vocab is vocab:
                    return self._columns
                # a SECOND scheduler over this cache brings its own mirror
                # Vocab: the interned spec rows are in the OLD vocab's
                # resource-slot order — silently reusing them would scatter
                # old-slot matrices into new-slot banks. Materialize every
                # view and rebuild the columns on the new vocab (the stale
                # per-pod slot memos are keyed by columns identity, so
                # they miss harmlessly).
                self._materialize_view(None)
                self._columns = None
            cols = CacheColumns(
                vocab, self._lock,
                capacity=max(len(self.snapshot.node_infos), 1),
            )
            for name, ni in self.snapshot.node_infos.items():
                row = cols.add_node_locked(name, ni.node.labels)
                cols.ingest_node_locked(row, ni)
            if self._deadlines is None:
                self._deadlines = AssumedDeadlines(self._lock)
                for key in self._assumed:
                    st = self._pod_states[key]
                    if st.binding_finished and st.deadline is not None:
                        self._deadlines.set_bulk_locked([key], st.deadline)
            if not isinstance(self.snapshot.node_infos, LazyNodeInfos):
                lazy = LazyNodeInfos(self.snapshot.node_infos)
                lazy._resolve = self._materialize_view
                self.snapshot.node_infos = lazy
            self._columns = cols
            return cols

    def _materialize_view(self, name: Optional[str]) -> None:
        """LazyNodeInfos resolver: replay the pending column journal into
        the named NodeInfo view (None = every stale row) before the
        object leaves the map. Raw dict access below — resolving through
        the lazy map again would recurse. Runs on WHICHEVER thread first
        reads the view, so even the columns-attached fast-path probe
        takes the (reentrant) lock — the pre-lock read KTPU003 caught
        could see a mid-detach columns object."""
        with self._lock:
            cols = self._columns
            if cols is None or not cols._stale_rows:
                return
            raw = self.snapshot.node_infos
            if name is not None:
                row = cols.row_of.get(name)
                if row is None or not cols.row_stale_locked(row):
                    return
                ni = dict.get(raw, name)
                if ni is not None:
                    cols.materialize_into_locked(name, ni)
                return
            for row in sorted(cols._stale_rows):
                nm = cols.name_of_row[row]
                ni = dict.get(raw, nm) if nm is not None else None
                if ni is not None:
                    cols.materialize_into_locked(nm, ni)

    def _drain_overgrown_locked(self) -> None:
        """Materialize rows whose lazy-view journal hit JOURNAL_BOUND —
        the deferral is an optimization, never an unbounded memory leak
        on a node nothing ever reads."""
        cols = self._columns
        raw = self.snapshot.node_infos
        for row in list(cols._overgrown):
            nm = cols.name_of_row[row]
            ni = dict.get(raw, nm) if nm is not None else None
            if ni is not None:
                cols.materialize_into_locked(nm, ni)
            else:
                cols._overgrown.discard(row)

    def detach_columns(self) -> None:
        """RUNTIME kill switch for the columnar plane (the fault plane's
        columns recovery): materialize every lazy NodeInfo view from its
        journal, then drop the columns and the deadline column — the
        legacy object paths take over exactly as KTPU_COLUMNAR_CACHE=0
        would have from the start. Object truth is complete because the
        journal is appended BEFORE the column scatter (columns.py
        _bulk_locked), so even a scatter that died mid-batch left a full
        replay log. Idempotent; re-attach later via attach_columns."""
        with self._lock:
            self._detach_columns_locked()

    # ktpu: holds(self._lock)
    def _detach_columns_locked(self) -> None:
        cols = self._columns
        if cols is None:
            return
        # rows with journaled ops whose scatter never completed are not
        # in _stale_rows yet — mark them so the full materialize below
        # replays EVERY pending op into the object views
        for row, ops in enumerate(cols._pending):
            if ops:
                cols._stale_rows.add(row)
        self._materialize_view(None)
        self._columns = None
        self._deadlines = None  # cleanup_expired falls back to the legacy walk

    # ktpu: holds(self._lock)
    def _columns_fault_locked(self, exc: Exception) -> None:
        """A columnar scatter raised mid-update: the columns are garbage
        but object truth is recoverable (journal-before-scatter), so
        detach inline — the CURRENT operation completes on the object
        path semantics — and report to the fault sink (the driver's
        breaker board force-trips: broken columns are known-wrong state,
        not a counted suspicion). The breaker's half-open probe
        re-attaches fresh columns and the columns-vs-banks shadow audit
        gates the close."""
        self._detach_columns_locked()
        sink = self.fault_sink
        if sink is not None:
            sink("columns", type(exc).__name__, True)

    # -- helpers -------------------------------------------------------------

    def _node_info(self, name: str) -> Optional[NodeInfo]:
        return self.snapshot.get(name)

    # ktpu: holds(self._lock) every caller is a locked cache mutator (the
    # cols.*_locked calls below already require it)
    def _add_pod_to_node(self, pod: Pod, folded: bool = False) -> None:
        # snapshot.get resolves the lazy view first (columnar mode), so
        # the eager object update below lands in journal order
        ni = self.snapshot.get(pod.node_name)
        cols = self._columns
        if ni is None:
            # pod on an unknown node: track headlessly (reference keeps an
            # imaginary NodeInfo; it becomes real when the node arrives)
            ni = self.snapshot.add_node(Node(name=pod.node_name))
            ni.node.labels = {}
            ni.add_pod(pod)
            if cols is not None:
                try:
                    row = cols.add_node_locked(pod.node_name, {})
                    cols.apply_one_locked(row, pod, 1)
                except Exception as e:
                    self._columns_fault_locked(e)
            self.dirty_nodes.add(pod.node_name)
            self.mutation_count += 1
            return
        ni.add_pod(pod)
        if cols is not None:
            try:
                cols.apply_one_locked(cols.row_of[pod.node_name], pod, 1)
            except Exception as e:
                self._columns_fault_locked(e)
        self.mutation_count += 1
        # single-pod change: a DELTA, not node dirt — the mirror patches the
        # node row + signature/pattern counts in O(1) instead of re-counting
        # every pod on the node
        self._push_delta(pod.node_name, pod, 1, folded)

    # ktpu: holds(self._lock)
    def _remove_pod_from_node(self, pod: Pod) -> None:
        ni = self.snapshot.get(pod.node_name)
        if ni is None:
            return
        removed = ni.remove_pod_key(pod.key())
        if removed is not None:
            cols = self._columns
            if cols is not None:
                try:
                    cols.apply_one_locked(cols.row_of[pod.node_name], removed, -1)
                except Exception as e:
                    self._columns_fault_locked(e)
            self.mutation_count += 1
            self._push_delta(pod.node_name, removed, -1)

    def _collapse_deltas_locked(self) -> None:
        """The ONE delta-log bound: with no mirror attached (or one that
        syncs rarely) the log must not pin every churned Pod forever —
        past the bound, collapse it into the node-count-bounded dirty
        set. A re-encoded node row ships fully, so collapsed FOLDED
        deltas stay correct: host wins the whole row. The scalar path
        checks per push (_push_delta); the bulk paths append raw in
        their loops and check once per batch."""
        if len(self.pod_deltas) >= max(1024, 4 * len(self.snapshot.node_infos)):
            for n, _, _, _ in self.pod_deltas:
                self.dirty_nodes.add(n)
            self.pod_deltas.clear()

    def _push_delta(self, name: str, pod: Pod, sign: int, folded: bool = False) -> None:
        self.pod_deltas.append((name, pod, sign, folded))
        self._collapse_deltas_locked()

    # -- assumed pod state machine (cache.go:270-388) ------------------------

    def assume_pod(self, pod: Pod) -> None:
        """AssumePod: optimistically add to the target node before bind."""
        with self._lock:
            key = pod.key()
            if key in self._pod_states:
                raise ValueError(f"pod {key} already in cache")
            self._pod_states[key] = _PodState(pod=pod, assumed=True)
            self._assumed.add(key)
            self._add_pod_to_node(pod)

    def assume_pods(self, pods: List[Pod], folded: bool = False) -> List[int]:
        """Bulk AssumePod under ONE lock (the per-pod RLock round-trip was
        a measurable slice of the commit loop at 4096-pod batches). Returns
        the indices of pods REJECTED because their key is already in the
        cache — the caller fails those individually (assume_pod's
        ValueError, per pod). `folded=True` tags the pushed deltas as
        already device-folded (resident-state plane) — the caller must
        have dispatched the matching fold_commit, and must report any
        REJECTED index's node via TensorMirror.note_failed_fold (its fold
        lane landed on device but no delta will reach the host)."""
        rejected: List[int] = []
        with self._lock:
            states = self._pod_states
            assumed = self._assumed
            cols = self._columns
            if cols is None:
                for i, pod in enumerate(pods):
                    key = pod.key()
                    if key in states:
                        rejected.append(i)
                        continue
                    states[key] = _PodState(pod=pod, assumed=True)
                    assumed.add(key)
                    self._add_pod_to_node(pod, folded)
                return rejected
            # COLUMNAR bulk assume: per pod only the state-machine dict
            # inserts + a journal append survive — the NodeInfo/Quantity
            # object walk is gone; the columns advance by one vectorized
            # scatter of the interned per-spec delta rows (the same rows
            # the fold plane ships to the device banks). The delta pushes
            # are inlined with one hoisted bound check (the per-pod
            # _push_delta call + bound recompute was a measurable slice
            # of the loop at 4096-pod batches).
            row_of = cols.row_of
            deltas = self.pod_deltas
            acc_rows: List[int] = []
            acc_pods: List[Pod] = []
            for i, pod in enumerate(pods):
                key = pod.key()
                if key in states:
                    rejected.append(i)
                    continue
                states[key] = _PodState(pod=pod, assumed=True)
                assumed.add(key)
                row = row_of.get(pod.node_name)
                if row is None:
                    # unknown node: the eager headless path (creates the
                    # placeholder NodeInfo and its columns row)
                    self._add_pod_to_node(pod, folded)
                    continue
                acc_rows.append(row)
                acc_pods.append(pod)
                deltas.append((pod.node_name, pod, 1, folded))
            self._bulk_scatter_locked(cols, acc_rows, acc_pods)
        return rejected

    # ktpu: holds(self._lock) shared tail of the columnar bulk adders
    def _bulk_scatter_locked(self, cols, acc_rows: List[int],
                             acc_pods: List[Pod]) -> None:
        """The columnar bulk-add scatter tail (assume_pods / add_pods —
        ONE copy of the collapse-then-scatter, fault-fallback, and
        overgrown-drain discipline): collapse the memoized delta sources
        first, scatter the accumulated rows, and on a scatter fault
        detach to object truth (journal-before-scatter makes the replay
        complete, this batch included)."""
        if not acc_pods:
            return
        self._collapse_deltas_locked()
        try:
            cols.assume_bulk_locked(acc_rows, acc_pods)
        except Exception as e:
            self._columns_fault_locked(e)
        self.mutation_count += len(acc_pods)
        if self._columns is not None and cols._overgrown:
            self._drain_overgrown_locked()

    def finish_binding(self, pod: Pod) -> None:
        """FinishBinding: start the TTL clock (cache.go:300)."""
        with self._lock:
            st = self._pod_states.get(pod.key())
            if st is None or not st.assumed:
                return
            st.binding_finished = True
            st.deadline = self._now() + self._ttl
            if self._deadlines is not None:
                self._deadlines.set_bulk_locked([pod.key()], st.deadline)

    def finish_bindings(self, pods: List[Pod]) -> None:
        """Bulk FinishBinding: one lock + one clock read for a whole bind
        chunk."""
        with self._lock:
            deadline = self._now() + self._ttl
            done = [] if self._deadlines is not None else None
            for pod in pods:
                st = self._pod_states.get(pod.key())
                if st is None or not st.assumed:
                    continue
                st.binding_finished = True
                st.deadline = deadline
                if done is not None:
                    done.append(pod.key())
            if done:
                self._deadlines.set_bulk_locked(done, deadline)

    def forget_pod(self, pod: Pod) -> None:
        """ForgetPod: bind failed; undo the assume (cache.go:334)."""
        with self._lock:
            key = pod.key()
            st = self._pod_states.get(key)
            if st is None or not st.assumed:
                return
            self._remove_pod_from_node(st.pod)
            del self._pod_states[key]
            self._assumed.discard(key)
            if self._deadlines is not None:
                self._deadlines.discard_locked(key)

    def forget_pods(self, pods: List[Pod]) -> None:
        """Bulk ForgetPod under ONE lock — the gang-rollback counterpart of
        assume_pods (commit/apply.GangRollbackRecord unwinds a whole group
        with one call). Pods not in the assumed state are skipped, exactly
        like forget_pod."""
        with self._lock:
            cols = self._columns
            if cols is None:
                for pod in pods:
                    key = pod.key()
                    st = self._pod_states.get(key)
                    if st is None or not st.assumed:
                        continue
                    self._remove_pod_from_node(st.pod)
                    del self._pod_states[key]
                    self._assumed.discard(key)
                return
            # COLUMNAR bulk forget: the exact integer inverse of the bulk
            # assume — one vectorized subtract, journaled removes
            states = self._pod_states
            assumed = self._assumed
            dl = self._deadlines
            deltas = self.pod_deltas
            acc_rows: List[int] = []
            acc_pods: List[Pod] = []
            for pod in pods:
                key = pod.key()
                st = states.get(key)
                if st is None or not st.assumed:
                    continue
                p = st.pod
                del states[key]
                assumed.discard(key)
                dl.discard_locked(key)
                row = cols.row_of.get(p.node_name)
                if row is None:
                    self._remove_pod_from_node(p)  # node vanished since
                    continue
                acc_rows.append(row)
                acc_pods.append(p)
                deltas.append((p.node_name, p, -1, False))
            if acc_pods:
                self._collapse_deltas_locked()
                try:
                    cols.forget_bulk_locked(acc_rows, acc_pods)
                except Exception as e:
                    self._columns_fault_locked(e)
                self.mutation_count += len(acc_pods)
                if self._columns is not None and cols._overgrown:
                    self._drain_overgrown_locked()

    # -- informer-confirmed pod events (cache.go:389-520) --------------------

    def add_pod(self, pod: Pod) -> None:
        """AddPod: informer says the pod is bound. Confirms an assumed pod or
        adds a foreign one."""
        with self._lock:
            key = pod.key()
            st = self._pod_states.get(key)
            if st is not None and st.assumed:
                # confirmation: replace the assumed object with the real one
                # (the informer may report a different node than we assumed —
                # removing from the OLD node handles both cases)
                self._remove_pod_from_node(st.pod)
                self._add_pod_to_node(pod)
                self._pod_states[key] = _PodState(pod=pod)
                self._assumed.discard(key)
                if self._deadlines is not None:
                    self._deadlines.discard_locked(key)
                return
            if st is not None:
                if (pod.resource_version
                        and st.pod.resource_version == pod.resource_version):
                    # re-delivery of the exact object already held (the
                    # store bumps resourceVersion on every write, so an
                    # equal rv IS the same object): no-op. Matters at
                    # cold start, where the informer's initial sweep
                    # re-delivers every pod the bulk columnar re-assume
                    # just added — the scalar remove/re-add walk would
                    # materialize lazy column views per pod, degrading
                    # reconciliation back to the O(pods) object walk.
                    return
                self.update_pod(st.pod, pod)
                return
            self._pod_states[key] = _PodState(pod=pod)
            self._add_pod_to_node(pod)

    def add_pods(self, pods: List[Pod]) -> int:
        """Bulk AddPod for the cold-start reconciliation path
        (kubernetes_tpu/restart): a relist's BOUND pods re-enter the
        cache as CONFIRMED state (never assumed — the API server already
        holds their bindings; re-assume-then-confirm would arm TTL
        clocks for binds that finished in a previous process lifetime).
        Rides the columnar plane exactly like assume_pods — one
        vectorized scatter of the interned per-spec delta rows, zero
        per-pod NodeInfo/Quantity object work — so reconciling a
        100k-pod cluster costs O(batch), not O(pods) object walks. Pods
        whose key is already tracked take the scalar add_pod confirm/
        update path (idempotent re-delivery); pods on unknown nodes take
        the eager headless path. Returns the number newly added."""
        added = 0
        dup: List[Pod] = []
        with self._lock:
            states = self._pod_states
            cols = self._columns
            acc_rows: List[int] = []
            acc_pods: List[Pod] = []
            for pod in pods:
                key = pod.key()
                if key in states:
                    dup.append(pod)
                    continue
                added += 1
                states[key] = _PodState(pod=pod)
                if cols is None:
                    self._add_pod_to_node(pod)
                    continue
                row = cols.row_of.get(pod.node_name)
                if row is None:
                    self._add_pod_to_node(pod)
                    continue
                acc_rows.append(row)
                acc_pods.append(pod)
                self.pod_deltas.append((pod.node_name, pod, 1, False))
            self._bulk_scatter_locked(cols, acc_rows, acc_pods)
        for pod in dup:
            self.add_pod(pod)
        return added

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._lock:
            self._remove_pod_from_node(old)
            self._add_pod_to_node(new)
            self._pod_states[new.key()] = _PodState(pod=new)

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.key()
            st = self._pod_states.pop(key, None)
            self._assumed.discard(key)
            if self._deadlines is not None:
                self._deadlines.discard_locked(key)
            if st is not None:
                self._remove_pod_from_node(st.pod)

    def is_assumed(self, key: str) -> bool:
        with self._lock:
            return key in self._assumed

    def known_keys(self, keys) -> Set[str]:
        """Subset of `keys` already tracked by the cache (assumed OR
        confirmed) — one lock for a whole batch. The commit plane's
        pre-apply check: a key in here would be REJECTED by assume_pods,
        so the caller can fail it synchronously with exact accounting."""
        with self._lock:
            states = self._pod_states
            return {k for k in keys if k in states}

    def assumed_count(self) -> int:
        """Pods assumed but not yet confirmed by the informer echo."""
        with self._lock:
            return len(self._assumed)

    def cleanup_expired(self) -> List[Pod]:
        """cleanupAssumedPods (cache.go:658): drop assumed pods whose bind
        confirmation never arrived within TTL (self-healing after lost
        binds). Returns the expired pods so the driver can re-queue them.

        Columnar mode: the candidate set comes from ONE vectorized
        compare over the deadline column (`deadline < now`) instead of a
        per-pod TTL walk under the cache lock every cycle; each hit is
        re-validated against the state machine before eviction (a slot
        whose pod moved on via an informer update is dropped, never
        re-fired)."""
        with self._lock:
            now = self._now()
            expired = []
            if self._deadlines is not None:
                for key in self._deadlines.expired_locked(now):
                    st = self._pod_states.get(key)
                    if (
                        st is None
                        or not st.assumed
                        or not st.binding_finished
                        or st.deadline is None
                        or now <= st.deadline
                    ):
                        self._deadlines.discard_locked(key)
                        continue
                    expired.append(st.pod)
                    self._remove_pod_from_node(st.pod)
                    del self._pod_states[key]
                    self._assumed.discard(key)
                    self._deadlines.discard_locked(key)
                return expired
            for key in list(self._assumed):
                st = self._pod_states[key]
                if st.binding_finished and st.deadline is not None and now > st.deadline:
                    expired.append(st.pod)
                    self._remove_pod_from_node(st.pod)
                    del self._pod_states[key]
                    self._assumed.discard(key)
            return expired

    # -- node events (cache.go:522-600) --------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            ni = self.snapshot.get(node.name)
            if ni is None:
                self.snapshot.add_node(node)
                self.node_tree.add_node(node)
            else:
                self.node_tree.update_node(ni.node, node)
                ni.node = node  # was a headless placeholder
            cols = self._columns
            if cols is not None:
                if node.name in cols.row_of:
                    cols.set_zone_locked(node.name, node.labels)
                else:
                    cols.add_node_locked(node.name, node.labels)
            self.dirty_nodes.add(node.name)
            self.removed_nodes.discard(node.name)
            self.mutation_count += 1

    def update_node(self, node: Node) -> None:
        self.add_node(node)

    def remove_node(self, name: str) -> None:
        with self._lock:
            # the lazy map's pop resolves the view first — the pods list
            # below must be current before the object leaves the map
            ni = self.snapshot.node_infos.pop(name, None)
            if ni is not None:
                self.node_tree.remove_node(ni.node)
                for p in ni.pods:
                    self._pod_states.pop(p.key(), None)
                    self._assumed.discard(p.key())
                    if self._deadlines is not None:
                        self._deadlines.discard_locked(p.key())
            if self._columns is not None:
                self._columns.remove_node_locked(name)
            self.dirty_nodes.discard(name)
            self.removed_nodes.add(name)
            self.mutation_count += 1

    def node_order(self) -> List[str]:
        """Zone-interleaved iteration order (NodeTree.Next semantics) for
        host-side placement loops; falls back to insertion order for nodes
        the tree doesn't know (headless placeholders)."""
        with self._lock:
            order = [n for n in self.node_tree.order() if n in self.snapshot.node_infos]
            if len(order) != len(self.snapshot.node_infos):
                seen = set(order)
                order.extend(n for n in self.snapshot.node_infos if n not in seen)
            return order

    # -- counters ------------------------------------------------------------

    def node_count(self) -> int:
        with self._lock:
            return len(self.snapshot.node_infos)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    def census(self) -> Dict:
        """One lock-disciplined snapshot of the cache's steady-state
        health (obs/introspect): object-state counts, the delta-log
        backlog, and (columnar mode) the columns' own census. Counters
        and metadata only — len() on the lazy node_infos map is a raw
        key count, resolving nothing."""
        with self._lock:
            cols = self._columns
            return {
                "nodes": len(self.snapshot.node_infos),
                "pods": len(self._pod_states),
                "assumed": len(self._assumed),
                "pending_deltas": len(self.pod_deltas),
                "dirty_nodes": len(self.dirty_nodes),
                "mutation_count": int(self.mutation_count),
                "columns": cols.census_locked() if cols is not None else None,
            }


class TensorMirror:
    """Keeps device-facing banks (NodeBank + SigBank + PatternBank) patched
    from a SchedulerCache — the TPU replacement for UpdateNodeInfoSnapshot's
    generation walk (cache.go:206-242). Node rows are allocated from a free
    list; each node's pods are COUNTED into label signatures (SigBank) and
    their (anti-)affinity terms into term patterns (PatternBank), and
    sync() re-counts ONLY the pods of dirty nodes — patch cost is
    proportional to the delta, not the cluster.

    Capacity overflow (more nodes/pods than the banks, label-key growth)
    triggers a full rebuild at the next bucket size — bounded recompilation
    by construction.
    """

    def __init__(self, cache: SchedulerCache, vocab: Optional[Vocab] = None):
        self.cache = cache
        self.vocab = vocab or Vocab()
        self.rebuild_count = -1  # constructor's build doesn't count
        self._min_nodes = 1
        # distinct (ns, labels) signatures are workload-bounded (hundreds in
        # 100k-pod clusters); starting at 256 avoids the mid-run SigOverflow
        # rebuild + solve recompile that a cold 16-slot bank pays on every
        # realistic workload. counts[N, 256] int16 is ~5 MB at 10k nodes.
        self._min_sigs = 256
        # distinct term patterns are even fewer (one per controller spec
        # carrying affinity, not per replica)
        self._min_pats = 32
        # device-resident copies of the banks, patched by dirty ROW SLICES:
        # on a remote-attached TPU, re-uploading whole banks every batch
        # costs seconds (10s of MB at ~15 MB/s tunnel bandwidth) — only the
        # changed rows may cross the wire (the device half of the
        # UpdateNodeInfoSnapshot generation walk, cache.go:206-242)
        self._dev_nodes = None
        self._dev_eps = None
        self._dev_pats = None
        self._device_stale = True
        self._image_stale = False
        self._pending_node_rows: Set[int] = set()
        # rows whose ONLY change since the last upload is pod-driven usage
        # (requested / nonzero_req / pod_count / signature counts): the
        # common post-commit case. Patching those ships 4 small arrays
        # instead of the full ~25-array row set — at 4096 commits/batch the
        # difference is ~90ms -> ~10ms of patch per batch on the tunnel.
        self._pending_usage_rows: Set[int] = set()
        # usage rows whose delta pod carried (anti-)affinity terms: only
        # those change the pattern-count matrix
        self._pending_pat_rows: Set[int] = set()
        # --- resident-state plane (ops/fold, commit/fold) ---------------
        # rows whose deltas since the last upload were applied ON DEVICE
        # by a commit fold: device == host for those rows already, so
        # device_arrays() must NOT re-ship them. A row appearing in BOTH a
        # folded and a pending set ships anyway — the host scatter is a
        # full-value overwrite, so host always wins on overlap.
        # the fold bookkeeping is DRIVER-THREAD-CONFINED: folds dispatch on
        # the driver thread before the commit worker sees the batch, and
        # sync() drains the pipeline first — declared confined so an
        # access from an unmarked method trips KTPU003 immediately
        self._folded_usage_rows: Set[int] = set()  # ktpu: confined(driver)
        self._folded_pat_rows: Set[int] = set()  # ktpu: confined(driver)
        # device-fold generation tag: how many folds the resident banks
        # carry beyond `device_generation` (the host sync generation the
        # last full/row upload reflected). Purely observational — the row
        # sets above are the operative bookkeeping.
        self.fold_count = 0
        self.folds_undonated = 0  # folds whose donation silently copied
        self.device_generation = 0
        # nominee overlay in flight: (rows, vecs, cnt) to fold back out
        # (integer adds are exactly invertible). Every resident-bank
        # consumer calls _restore_nominees() first, so a caller that died
        # between fold and unfold cannot leave the banks corrupted.
        self._nominee_overlay = None  # ktpu: confined(driver)
        # fold lanes whose cache assume was REJECTED after dispatch (the
        # informer race): their node rows must re-ship from host. Appended
        # by the commit worker; drained by sync(). Cross-thread by design,
        # so it takes the cache lock on BOTH sides (KTPU003 discipline —
        # the old GIL-atomic-append argument was true but unverifiable).
        self._failed_fold_names: List[str] = []  # ktpu: guarded-by(cache._lock)
        # host→device traffic ledger, by kind (full|rows|usage|fold) —
        # also exported as scheduler_mirror_bytes_shipped_total
        self.bytes_shipped: Dict[str, int] = {}
        # the driver opts patches into buffer donation once it owns the
        # only live reference to the bank dicts (fold plane on)
        self.donate_patches = False
        # the driver's compile plan (when attached): the dirty-row scatter
        # programs are admitted as KIND_PATCH specs so a post-warmup
        # scatter compile is a VISIBLE miss, not a silent mid-drain stall
        self.compile_plan = None
        # fault plane (kubernetes_tpu/faults): patch-scatter failures
        # report here (the driver's breaker board) and self-heal via the
        # full-upload path; fault_plan arms the device-raise:patch
        # injection site. Both default None — one attribute read each.
        self.fault_sink = None
        self.fault_plan = None
        # mesh-bound fold kernels (ops/fold.make_sharded_fold_fns), built
        # lazily on first fold after set_mesh
        self._sharded_folds = None
        self._rebuild()

    def reserve(self, n_nodes: int, n_pods: int = 0) -> None:
        """Pre-size the banks for an expected cluster scale. Every bank
        growth changes array shapes and costs an XLA recompile (minutes on a
        remote TPU), so callers that know their scale up front — benchmarks,
        a scheduler fed a full initial list — should reserve once. Existing
        pods are held as label SIGNATURES whose distinct count is workload-
        dependent (not pod-count-dependent), so `n_pods` no longer sizes
        that bank — the signature bucket grows on demand."""
        self._min_nodes = max(self._min_nodes, n_nodes)
        if _node_bucket(self._min_nodes) > self.nodes.capacity:
            self._rebuild()

    def reserve_signatures(self, n_sigs: int, n_pats: int = 0) -> bool:
        """Pre-size the signature/pattern banks for a KNOWN workload —
        the driver's warmup census walks the full pending queue and calls
        this so committing the backlog cannot overflow the banks mid-
        drain (each overflow is a full rebuild + solve recompile: the
        gang bench's `mirror_rebuilds: 1`). A growth here rebuilds once,
        at SETUP time; like the constructor's build — and unlike a
        mid-drain overflow rebuild — it is excluded from rebuild_count,
        which stays the mid-drain stall counter the bench asserts on.
        Returns True when a rebuild happened (device arrays re-upload)."""
        grew = False
        if n_sigs > self.eps.capacity:
            self._min_sigs = max(self._min_sigs, n_sigs)
            grew = True
        if n_pats > self.pats.capacity:
            self._min_pats = max(self._min_pats, n_pats)
            grew = True
        if grew:
            rc = self.rebuild_count
            self._rebuild()
            self.rebuild_count = rc
        return grew

    def census_reserve(self, pods) -> bool:
        """Count the distinct signatures/patterns committing `pods` would
        intern and pre-size the banks for them (reserve_signatures) —
        the warmup census. Lives HERE, next to the banks whose interning
        identity it must mirror: SigBank keys by (label row, namespace,
        deleting) — pending pods are never deleting, so the (labels, ns)
        tuple below is that identity without touching the interner —
        and PatternBank keys by its own _key over _pod_patterns."""
        sigs: Set[tuple] = set()
        pats: Set[tuple] = set()
        seen_aff: Set[tuple] = set()
        for p in pods:
            sigs.add((tuple(sorted(p.labels.items())), p.namespace))
            if p.affinity is not None:
                sk = (p.namespace, repr(p.affinity))
                if sk not in seen_aff:
                    seen_aff.add(sk)
                    for args in self.pats._pod_patterns(p):
                        pats.add(self.pats._key(*args))
        # the backlog interns ALONGSIDE whatever the existing cluster
        # already holds; modest headroom on top (growth past it is still
        # covered by the ladder's s*4/pt*4 headroom warming)
        n_sigs = len(self.eps._sig_of) + len(sigs)
        n_pats = len(self.pats._row_of) + len(pats)
        return self.reserve_signatures(
            n_sigs + max(8, n_sigs // 8),
            n_pats + max(8, n_pats // 8) if pats else 0,
        )

    # ktpu: confined(driver) driver-thread only: constructor/reserve/sync
    def _rebuild(self) -> None:
        self.rebuild_count += 1
        snap = self.cache.snapshot
        while True:
            try:
                n_nodes = max(len(snap.node_infos), self._min_nodes, 1)
                self.nodes = NodeBank(self.vocab, _node_bucket(n_nodes))
                self.row_of: Dict[str, int] = {}
                self.name_of_row: List[Optional[str]] = [None] * self.nodes.capacity
                self._free_rows = list(range(self.nodes.capacity - 1, -1, -1))
                for ni in snap.node_infos.values():
                    row = self._free_rows.pop()
                    self.row_of[ni.node.name] = row
                    self.name_of_row[row] = ni.node.name
                    self.nodes.set_node(row, ni)
                self.eps = SigBank(
                    self.vocab, _bucket(self._min_sigs), self.nodes.capacity
                )
                self.pats = PatternBank(
                    self.vocab, _bucket(self._min_pats), self.nodes.capacity
                )
                self._node_sigs: Dict[str, Dict[int, int]] = {}
                self._node_pats: Dict[str, Dict[int, int]] = {}
                for name, ni in snap.node_infos.items():
                    self._encode_node_pods(name, ni)
                ImageTable(self.vocab).apply(self.nodes, snap, self.row_of)
                self._image_sig = {
                    name: self._image_signature(ni) for name, ni in snap.node_infos.items()
                }
                break
            except SigOverflow:
                # 4x per growth: each distinct signature capacity is a full
                # solve recompile — buy headroom, not tight fits
                self._min_sigs *= 4
            except PatternOverflow:
                self._min_pats *= 4
            except KeySlotOverflow:
                continue
        self.cache.dirty_nodes.clear()
        self.cache.removed_nodes.clear()
        self.cache.pod_deltas.clear()  # the rebuild re-counted everything
        self._device_stale = True  # shapes may have changed: full re-upload
        self._pending_node_rows.clear()
        self._pending_usage_rows.clear()
        self._pending_pat_rows.clear()
        self._folded_usage_rows.clear()
        self._folded_pat_rows.clear()
        # rebuild runs pre-concurrency (__init__/reserve at setup) or
        # inside sync()'s cache-lock block:
        # ktpu: allow(KTPU003) no concurrent writer can exist here
        self._failed_fold_names.clear()
        self._nominee_overlay = None  # donated buffers are gone with the banks
        self.eps.dirty_sig_rows.clear()
        self.pats.dirty_pattern_rows.clear()
        self.generation = 0

    @staticmethod
    def _image_signature(ni: NodeInfo):
        return frozenset(ni.image_sizes().items())

    def _release_node_pods(self, name: str) -> None:
        # a node can be added AND removed between syncs: it was never
        # encoded, so there is no row and nothing held
        row = self.row_of.get(name)
        if row is None:
            self._node_sigs.pop(name, None)
            self._node_pats.pop(name, None)
            return
        held = self._node_sigs.pop(name, None)
        if held:
            # callers must release BEFORE freeing the node row (sync() does):
            # release_node subtracts the held counts, restoring the row's
            # counts column to zero so a later node can reuse it cleanly
            self.eps.release_node(row, held)
            self._pending_node_rows.add(row)
        held_p = self._node_pats.pop(name, None)
        if held_p:
            self.pats.release_node(row, held_p)
            self._pending_node_rows.add(row)

    def _encode_node_pods(self, name: str, ni: NodeInfo) -> None:
        """Re-count one node's pods into label signatures and their terms
        into patterns. Raises SigOverflow/PatternOverflow/KeySlotOverflow
        when a bank is full (caller rebuilds bigger)."""
        node_row = self.row_of[name]
        self._node_sigs[name] = self.eps.encode_node(node_row, ni.pods)
        self._node_pats[name] = self.pats.encode_node(
            node_row, ni.pods_with_affinity()
        )
        self._pending_node_rows.add(node_row)

    # ktpu: confined(driver) the mirror's one sync entry point — driver
    # thread only (commit-worker writes arrive via note_failed_fold's
    # locked list, drained here under the same lock)
    def sync(self) -> bool:
        """Apply dirty nodes (and ONLY their pods) plus single-pod deltas
        (O(1) each — no per-node re-count). Returns True if a full rebuild
        happened (device arrays change shape → recompile)."""
        cache = self.cache
        self._restore_nominees()
        with cache._lock:
            # fold lanes whose assume was rejected after dispatch: the
            # device rows carry phantom deltas the host never applied —
            # force those rows back onto the host-wins patch path
            if self._failed_fold_names:
                names, self._failed_fold_names = self._failed_fold_names, []
                for nm in names:
                    row = self.row_of.get(nm)
                    if row is not None:
                        self._pending_usage_rows.add(row)
                        self._pending_pat_rows.add(row)
            dirty = set(cache.dirty_nodes)
            removed = set(cache.removed_nodes)
            deltas = list(cache.pod_deltas)
            cache.dirty_nodes.clear()
            cache.removed_nodes.clear()
            cache.pod_deltas.clear()
            has_new = any(n not in self.row_of for n in cache.snapshot.node_infos)
            if len(cache.snapshot.node_infos) > self.nodes.capacity:
                self._rebuild()
                return True
            if not (dirty or removed or has_new or deltas):
                return False
            try:
                for name in removed:
                    # release pods FIRST (zeroes the node's signature-count
                    # row) so a later node reusing this row starts clean
                    self._release_node_pods(name)
                    row = self.row_of.pop(name, None)
                    if row is not None:
                        self.nodes.clear_node(row)
                        self.name_of_row[row] = None
                        self._free_rows.append(row)
                        self._pending_node_rows.add(row)
                    self._image_sig.pop(name, None)
                new_nodes = [
                    n for n in cache.snapshot.node_infos if n not in self.row_of
                ]
                if len(new_nodes) > len(self._free_rows):
                    self._rebuild()
                    return True
                for name in new_nodes:
                    row = self._free_rows.pop()
                    self.row_of[name] = row
                    self.name_of_row[row] = name
                images_changed = bool(removed) or bool(new_nodes)
                for name in dirty | set(new_nodes):
                    ni = cache.snapshot.get(name)
                    if ni is None or name not in self.row_of:
                        continue
                    self.nodes.set_node(self.row_of[name], ni)
                    self._pending_node_rows.add(self.row_of[name])
                    # pods: release this node's old signature + pattern
                    # counts, re-count
                    self._release_node_pods(name)
                    self._encode_node_pods(name, ni)
                    sig = self._image_signature(ni)
                    if self._image_sig.get(name) != sig:
                        self._image_sig[name] = sig
                        images_changed = True
                # single-pod deltas last, skipping nodes that were fully
                # re-encoded above (their counts already include the deltas)
                reencoded = removed | dirty | set(new_nodes)
                # usage columns: apply the pod's request vector as a numpy
                # INCREMENT — numerically identical to re-reading
                # ni.requested(). Plain ADDS (the overwhelming case: one per
                # commit) batch into vectorized np.add.at scatters
                # (apply_adds_bulk / apply_pod_deltas_bulk); removes and
                # ported/affinity pods take the scalar path. The bulk buffer
                # flushes before every scalar delta so per-node ordering is
                # preserved exactly (a remove must see the adds before it).
                # Ports stay snapshot-refreshed (list-shaped).
                ports_dirty: Set[str] = set()
                bulk_rows: List[int] = []
                bulk_pods: List[Pod] = []
                bulk_held: List[Dict[int, int]] = []
                bulk_folded: List[bool] = []

                cols = cache._columns
                if cols is not None and cols.vocab is not self.vocab:
                    # columns rebuilt on another scheduler's Vocab: their
                    # slot order is not this mirror's — per-pod build
                    cols = None

                def flush_bulk() -> None:
                    if not bulk_pods:
                        return
                    rows_arr = np.asarray(bulk_rows, np.int64)
                    self.eps.apply_adds_bulk(rows_arr, bulk_pods, bulk_held)
                    # columnar plane: the delta matrices gather from the
                    # SAME interned spec rows the columns (and the fold
                    # plane) advance by — one delta source, one overflow
                    # contract (KeySlotOverflow → the rebuild below)
                    mats = (
                        cols.delta_mats_locked(
                            bulk_pods, self.nodes.requested.shape[1]
                        )
                        if cols is not None
                        else None
                    )
                    self.nodes.apply_pod_deltas_bulk(rows_arr, bulk_pods, mats=mats)
                    # device-FOLDED adds already live in the resident
                    # banks: their rows go to the folded set (skipped at
                    # upload) instead of the pending set (shipped)
                    for r, f in zip(bulk_rows, bulk_folded):
                        (self._folded_usage_rows if f
                         else self._pending_usage_rows).add(r)
                    bulk_rows.clear()
                    bulk_pods.clear()
                    bulk_held.clear()
                    bulk_folded.clear()

                for name, pod, sign, folded in deltas:
                    if name in reencoded or name not in self.row_of:
                        continue
                    row = self.row_of[name]
                    if (
                        sign > 0
                        and not pod.host_ports()
                        and not pod_has_affinity_constraints(pod)
                    ):
                        bulk_rows.append(row)
                        bulk_pods.append(pod)
                        bulk_held.append(self._node_sigs.setdefault(name, {}))
                        bulk_folded.append(folded)
                        continue
                    flush_bulk()
                    self.eps.apply_delta(
                        row, pod, sign, self._node_sigs.setdefault(name, {})
                    )
                    # only ADDS fold (commits); a folded flag on anything
                    # else is ignored — the pending (host-wins) path is
                    # always safe
                    f = folded and sign > 0
                    if pod_has_affinity_constraints(pod):
                        self.pats.apply_delta(
                            row, pod, sign, self._node_pats.setdefault(name, {})
                        )
                        (self._folded_pat_rows if f
                         else self._pending_pat_rows).add(row)
                    self.nodes.apply_pod_delta(row, pod, sign)
                    if pod.host_ports():
                        # the port table changed too (list-shaped, not
                        # foldable): the full-row refresh below ships the
                        # row — host wins regardless of the fold
                        ports_dirty.add(name)
                        f = False
                    (self._folded_usage_rows if f
                     else self._pending_usage_rows).add(row)
                flush_bulk()
                # ported pods and fallback rows: the port table is a sorted
                # list snapshot — refresh those nodes fully (rare)
                for name in ports_dirty:
                    ni = cache.snapshot.get(name)
                    if ni is None:
                        continue
                    row = self.row_of[name]
                    if not self.nodes.update_usage(row, ni):
                        self.nodes.set_node(row, ni)
                    # port arrays changed: usage-only patching won't ship them
                    self._pending_node_rows.add(row)
                if images_changed:
                    # spread scaling depends on cluster-wide image placement
                    # and node count → recompute the whole table (rare: image
                    # states and node membership change far less than pods)
                    ImageTable(self.vocab).apply(self.nodes, cache.snapshot, self.row_of)
                    self._image_stale = True
            except KeySlotOverflow:
                self._rebuild()
                return True
            self.generation += 1
            return False

    # ktpu: confined(driver) fault-plane recovery primitive
    def mark_device_stale(self) -> None:
        """Force the next device_arrays() to re-upload the FULL banks
        from host truth (host wins) — clears partially-applied folds,
        broken patches, or injected skew. The fault plane's resync
        action; a full upload is `_to_dev` placement of existing host
        arrays, so resync never meets the XLA compiler."""
        self._device_stale = True

    def set_mesh(self, mesh) -> None:
        """Keep the node-major device banks SHARDED-resident on `mesh`
        (leading axis split over the "nodes" mesh axis). Without this the
        sharded pipeline would reshard replicated inputs on every dispatch.
        Patches preserve the sharding (the jitted row-scatter's output
        inherits its input's), and commit folds dispatch through the
        mesh-bound shard_map kernels (ops/fold.make_sharded_fold_fns) so
        the resident-state plane keeps working on multi-chip meshes."""
        self._mesh = mesh
        self._sharded_folds = None  # rebuilt for the new mesh on demand
        self._device_stale = True  # next device_arrays re-uploads sharded

    def _to_dev(self, v, node_major: bool):
        import jax
        import jax.numpy as jnp

        if node_major and getattr(self, "_mesh", None) is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(
                jnp.asarray(v), NamedSharding(self._mesh, P("nodes"))
            )
        return jnp.asarray(v)

    # ktpu: confined(driver) driver-thread dispatch prologue; the commit
    # worker and uploader never call it (mirror confinement contract)
    def device_arrays(self):
        """(nodes, eps, pats) as DEVICE-resident dicts, patched with only
        the rows sync() touched since the last call — MINUS the rows a
        commit fold already applied on device (the resident-state plane:
        a covered steady-state batch ships nothing here at all). Full
        upload only after a rebuild (shape change) — otherwise each
        changed array ships one [rows, ...] slice + scatter; with
        `donate_patches` the resident buffers are donated into the
        scatter, so the banks update in place instead of being copied."""
        import jax.numpy as jnp

        self._restore_nominees()
        host_n = self.nodes.arrays()
        host_e = self.eps.arrays()
        host_p = self.pats.arrays()
        if self._dev_nodes is None or self._device_stale:
            self._dev_nodes = {k: self._to_dev(v, True) for k, v in host_n.items()}
            self._dev_eps = {
                k: self._to_dev(v, k == "counts") for k, v in host_e.items()
            }
            self._dev_pats = {
                k: self._to_dev(v, k == "counts") for k, v in host_p.items()
            }
            self._ship("full", sum(
                _nbytes(v)
                for d in (host_n, host_e, host_p)
                for v in d.values()
            ))
            self._device_stale = False
            self._image_stale = False
            self._pending_node_rows.clear()
            self._pending_usage_rows.clear()
            self._pending_pat_rows.clear()
            self._folded_usage_rows.clear()
            self._folded_pat_rows.clear()
            self.fold_count = 0
            self.device_generation = getattr(self, "generation", 0)
            self.eps.dirty_sig_rows.clear()
            self.pats.dirty_pattern_rows.clear()
            return self._dev_nodes, self._dev_eps, self._dev_pats

        scatter = (
            _row_scatter_donated_fn() if self.donate_patches
            else _row_scatter_fn()
        )

        import jax.dtypes

        def patch(dev: Dict, host: Dict, rows: List[int], skip=(), kind="rows") -> Dict:
            # full re-upload for new/resized arrays (rare: vocab growth);
            # compare against the CANONICALIZED dtype — with x64 disabled
            # jnp.asarray downcasts int64 host banks to int32 on device, and
            # a raw string compare would flag those every batch, shipping
            # whole banks and silently defeating the dirty-row design
            changed = {
                k: h
                for k, h in host.items()
                if k not in dev
                or dev[k].shape != h.shape
                or dev[k].dtype != jax.dtypes.canonicalize_dtype(h.dtype)
                or k in skip
            }
            if changed:
                dev = dict(dev)
                # node-major arrays: every nodes-bank array plus the banks'
                # per-node count matrices (leading axis = node capacity)
                dev.update({
                    k: self._to_dev(v, host is host_n or k == "counts")
                    for k, v in changed.items()
                })
                self._ship("full", sum(_nbytes(v) for v in changed.values()))
            if not rows:
                return dev
            return self._scatter_rows(scatter, dev, host, rows, kind)

        nrows = sorted(self._pending_node_rows)
        # usage-only rows (post-commit deltas): only 3 node arrays + the
        # banks' count matrices changed — ship those, not the whole row
        # set. Rows whose deltas were ALL device-folded appear in neither
        # set and ship NOTHING: device == host there by construction
        # (host wins any overlap — the scatter is a full-value overwrite).
        urows = sorted(self._pending_usage_rows - self._pending_node_rows)
        crows = sorted(self._pending_usage_rows | self._pending_node_rows)
        srows = sorted(self.eps.dirty_sig_rows)
        prows = sorted(self.pats.dirty_pattern_rows)
        skip_n = ("image_scaled",) if self._image_stale else ()
        try:
            self._dev_nodes = patch(self._dev_nodes, host_n, nrows, skip=skip_n)
            if urows:
                usage_host = {
                    k: host_n[k] for k in ("requested", "nonzero_req", "pod_count")
                }
                self._dev_nodes = patch(self._dev_nodes, usage_host, urows, kind="usage")
        except Exception as e:
            # patch-scatter fault (the fault plane's "mirror" boundary):
            # the device banks may be PARTIALLY patched — host wins.
            # Report to the breaker and fall back to the full-upload
            # path, which rebuilds every resident array from host truth
            # (placement only, no compiles) and clears the pending sets.
            sink = self.fault_sink
            if sink is not None:
                sink("mirror", type(e).__name__)
            self._device_stale = True
            return self.device_arrays()
        self._image_stale = False

        # the eps/pats dicts have TWO row spaces each: metadata ([S]/[PT]-
        # major, patched by dirty signature/pattern rows) and the per-node
        # count matrix ([N, *] node-major, patched by dirty NODE rows —
        # usage rows included: commits count pods into signatures)
        def patch_bank(dev, host, meta_rows, cnt_rows):
            meta_host = {k: v for k, v in host.items() if k != "counts"}
            meta_dev = {k: v for k, v in dev.items() if k != "counts"}
            meta_dev = patch(meta_dev, meta_host, meta_rows)
            cnt_dev = patch(
                {"counts": dev["counts"]}, {"counts": host["counts"]}, cnt_rows
            )
            return {**meta_dev, **cnt_dev}

        pat_crows = sorted(self._pending_pat_rows | self._pending_node_rows)
        try:
            self._dev_eps = patch_bank(self._dev_eps, host_e, srows, crows)
            self._dev_pats = patch_bank(self._dev_pats, host_p, prows, pat_crows)
        except Exception as e:
            # same patch-fault fallback as the node-bank section above
            sink = self.fault_sink
            if sink is not None:
                sink("mirror", type(e).__name__)
            self._device_stale = True
            return self.device_arrays()
        self._pending_node_rows.clear()
        self._pending_usage_rows.clear()
        self._pending_pat_rows.clear()
        # folded rows are settled: the fold applied them, and any overlap
        # with the pending sets just shipped host truth over them
        self._folded_usage_rows.clear()
        self._folded_pat_rows.clear()
        self.fold_count = 0
        self.device_generation = getattr(self, "generation", 0)
        self.eps.dirty_sig_rows.clear()
        self.pats.dirty_pattern_rows.clear()
        return self._dev_nodes, self._dev_eps, self._dev_pats

    def _patch_spec(self, host: Dict, rb: int, cap: int):
        """The dirty-row scatter's program identity as a compile-plan spec:
        one XLA program per (update-key structure WITH column widths, row
        rung, row capacity, donation). The widths matter: a vocab/bank
        growth widens arrays mid-drain, and the post-growth scatter is a
        genuinely new program — omitting widths would count it as a
        phantom HIT while it compiles inline."""
        from ..compile.ladder import KIND_PATCH, SolveSpec

        structure = ",".join(
            f"{k}{list(v.shape[1:])}" for k, v in sorted(host.items())
        )
        return SolveSpec(
            kind=KIND_PATCH, b=rb, n=cap,
            config_repr=(
                ("don|" if self.donate_patches else "copy|") + structure
            ),
        )

    def _scatter_rows(
        self, scatter, dev: Dict, host: Dict, rows, kind: str,
        warm: bool = False,
    ) -> Dict:
        """Ship `rows` of `host` and scatter them into `dev`, chunked at
        the PATCH_RUNGS quantizer so the program set stays small enough to
        pre-compile (warm_patches). Row padding repeats row[0] — an
        idempotent overwrite. Admitted against the attached compile plan:
        a scatter compile AFTER warmup is a counted miss (these were the
        invisible mid-drain stalls of the preemption bench — victim
        deletions dirtied rows at a fresh bucket and the scatter compiled
        inline, billed to solve_s). `warm=True` (warm_patches) DECLARES
        instead of admitting — planned pre-compiles must not inflate the
        dispatch miss counters."""
        import jax.numpy as jnp
        import numpy as _np

        from ..obs import NOOP_SPAN, RECORDER as _rec

        fp = self.fault_plan
        if fp is not None and not warm:  # injection site: one attr read
            fp.raise_if("device-raise", "patch")
        cap = next(iter(host.values())).shape[0]
        rb = min(_patch_rung(len(rows)), cap)
        plan = self.compile_plan
        known = True
        if plan is not None:
            spec = self._patch_spec(host, rb, cap)
            if warm:
                known = plan.is_declared(spec)
                plan.declare(spec)
            else:
                known = plan.admit(spec)
        rows = list(rows)
        first = True
        dt_compile = 0.0
        # flight-recorder "patch" span around the chunked scatters, on
        # whichever thread ships them (driver sync, warmup worker)
        with (_rec.span("patch", kind=kind, rows=len(rows), warm=warm)
              if _rec.enabled else NOOP_SPAN):
            for i in range(0, len(rows), rb):
                chunk = rows[i : i + rb]
                padded = chunk + [chunk[0]] * (rb - len(chunk))
                idx = _np.asarray(padded, _np.int32)
                updates = {k: _np.ascontiguousarray(h[idx]) for k, h in host.items()}
                self._ship(kind, idx.nbytes + sum(u.nbytes for u in updates.values()))
                if first:
                    # only the FIRST chunk can trace+compile (later chunks
                    # hit the fresh cache entry) — attribute just its wall
                    # to the miss, or compile_s would overstate the stall
                    # by the chunk count
                    t0 = time.perf_counter()
                    dev = scatter(dev, jnp.asarray(idx), updates)
                    dt_compile = time.perf_counter() - t0
                    first = False
                else:
                    dev = scatter(dev, jnp.asarray(idx), updates)
        if plan is not None and not known:
            from ..compile.plan import SOURCE_INLINE, SOURCE_WARMUP

            plan.note_compiled(
                spec, dt_compile,
                SOURCE_WARMUP if warm
                else (SOURCE_INLINE if plan.warmed else "warmup"),
            )
        return dev

    def warm_patches(self) -> int:
        """Pre-compile every dirty-row scatter program the mirror can ship
        (each bank structure x each PATCH_RUNGS rung ≤ its capacity) with
        idempotent no-op patches — row 0 repeated, host truth re-written
        over itself. Returns the number of scatter programs executed. The
        driver calls this at warmup so post-warmup patches (commit usage
        rows, preemption victim deletions, node churn) land on hot
        programs; without it the first patch at each fresh rung is an
        inline XLA compile billed mid-drain."""
        # like every resident-bank consumer: fold an active nominee
        # overlay back out first — the no-op scatters below rewrite rows
        # with HOST truth, which would erase overlay contributions and
        # leave the later unfold subtracting them into phantom capacity
        self._restore_nominees()
        if self._dev_nodes is None or self._device_stale:
            self.device_arrays()
        scatter = (
            _row_scatter_donated_fn() if self.donate_patches
            else _row_scatter_fn()
        )
        host_n = self.nodes.arrays()
        host_e = self.eps.arrays()
        host_p = self.pats.arrays()
        usage_h = {k: host_n[k] for k in ("requested", "nonzero_req", "pod_count")}
        n = 0
        # each entry mirrors ONE device_arrays patch call: (dev pytree,
        # host dict) must match it exactly or the warmed jit signature is
        # a different program than the one the drain dispatches
        for label, dev_of, host, sink in (
            # usage patches pass the FULL nodes dict as dev (3-key host)
            ("nodes", lambda: self._dev_nodes, host_n, "_dev_nodes"),
            ("usage", lambda: self._dev_nodes, usage_h, "_dev_nodes"),
            (
                "eps_meta",
                lambda: {k: v for k, v in self._dev_eps.items() if k != "counts"},
                {k: v for k, v in host_e.items() if k != "counts"},
                "_dev_eps",
            ),
            (
                "eps_counts",
                lambda: {"counts": self._dev_eps["counts"]},
                {"counts": host_e["counts"]},
                "_dev_eps",
            ),
            (
                "pats_meta",
                lambda: {k: v for k, v in self._dev_pats.items() if k != "counts"},
                {k: v for k, v in host_p.items() if k != "counts"},
                "_dev_pats",
            ),
            (
                "pats_counts",
                lambda: {"counts": self._dev_pats["counts"]},
                {"counts": host_p["counts"]},
                "_dev_pats",
            ),
        ):
            cap = next(iter(host.values())).shape[0]
            seen = set()
            for rung in PATCH_RUNGS:
                rb = min(rung, cap)
                if rb in seen:
                    continue  # rungs past capacity collapse onto one program
                seen.add(rb)
                out = self._scatter_rows(
                    scatter, dev_of(), host, [0] * rb, "warm", warm=True
                )
                setattr(self, sink, {**getattr(self, sink), **out})
                n += 1
        return n

    # -- resident-state plane (ops/fold + commit/fold) ----------------------

    def _ship(self, kind: str, nbytes: int) -> None:
        """Account host→device bank traffic (satellite of the fold plane:
        the win must be a measured byte count, not just patch_s)."""
        self.bytes_shipped[kind] = self.bytes_shipped.get(kind, 0) + int(nbytes)
        try:
            from ..metrics import metrics as M

            M.mirror_bytes_shipped.inc(kind, by=int(nbytes))
        except Exception:  # pragma: no cover - metrics must never break sync
            pass

    def can_fold(self) -> bool:
        """Device banks resident and current-shaped: the preconditions for
        folding commits in place. On a mesh the banks are node-sharded and
        the fold dispatches through the mesh-bound shard_map kernels
        (collective-free, sharding preserved through donation) — foldable
        whenever the node capacity divides the shard count, the same
        divisibility rule the sharded solve itself lives by."""
        if self._dev_nodes is None or self._device_stale:
            return False
        mesh = getattr(self, "_mesh", None)
        if mesh is None:
            return True
        from ..parallel.mesh import AXIS_NODES

        shards = mesh.shape.get(AXIS_NODES, 0)
        return shards > 0 and self.nodes.capacity % shards == 0

    def _fold_fns(self):
        """(fold_commit_banks, fold_usage) for the current residency: the
        plain donated kernels single-device, the mesh-bound shard_map
        twins when the banks are node-sharded."""
        if getattr(self, "_mesh", None) is None:
            from ..ops.fold import fold_commit_banks, fold_usage

            return fold_commit_banks, fold_usage
        if self._sharded_folds is None:
            from ..ops.fold import make_sharded_fold_fns

            self._sharded_folds = make_sharded_fold_fns(self._mesh)
        return self._sharded_folds

    # ktpu: hot-path
    def fold_commit(self, prog) -> bool:
        """Apply a planned commit fold (commit/fold.FoldProgram) to the
        resident banks with buffer donation. Returns False when the banks
        are not foldable right now (caller falls back to the host scatter
        path — correctness never depends on the fold landing). On a raise
        mid-dispatch the banks' state is unknown → full re-upload heals."""
        self._restore_nominees()
        if not self.can_fold():
            return False
        fold_commit_banks, _ = self._fold_fns()

        n, e, p = self._dev_nodes, self._dev_eps, self._dev_pats
        donated = (
            n["requested"], n["nonzero_req"], n["pod_count"],
            e["counts"], p["counts"],
        )
        try:
            req_d, nz_d, pc_d, ec_d, xc_d = fold_commit_banks(
                *donated,
                prog.rows, prog.req, prog.nz, prog.cnt, prog.sig,
                prog.pat_row, prog.pat_col, prog.pat_cnt,
            )
        except Exception:
            self._device_stale = True
            raise
        self._dev_nodes = {
            **n, "requested": req_d, "nonzero_req": nz_d, "pod_count": pc_d,
        }
        self._dev_eps = {**e, "counts": ec_d}
        self._dev_pats = {**p, "counts": xc_d}
        self.fold_count += 1
        if any(not a.is_deleted() for a in donated):
            # a dropped donation is silent in XLA: the fold still lands,
            # but that bank was COPIED (double HBM + hidden memcpy) —
            # the counts matrices are the largest and likeliest to hit an
            # aliasing restriction, so every donated input is checked.
            # Counted so perf_smoke can assert it never happens.
            self.folds_undonated += 1
        self._ship("fold", prog.nbytes)
        return True

    def note_failed_fold(self, node_name: str) -> None:
        """A fold lane's cache assume was rejected AFTER the fold
        dispatched (informer race): the device row carries a delta the
        host never applied. Queue the row for a host-wins re-ship at the
        next sync. Called from the COMMIT WORKER — the one mirror entry
        point off the driver thread — so it serializes on the cache lock
        (reentrant: the worker already holds it inside assume paths)."""
        cache = self.cache
        with cache._lock:
            self._failed_fold_names.append(node_name)

    # ktpu: hot-path; confined(driver) dispatch path
    def fold_nominees(self, rows: np.ndarray, vecs: np.ndarray, cnt: np.ndarray):
        """Overlay out-of-batch nominees' requests onto the resident usage
        columns IN PLACE (donation) — the nominee accounting of
        podFitsOnNode pass 1, without the full-bank copy the old jitted
        overlay paid per dispatch. The overlay is recorded and folded back
        out by unfold_nominees (integer adds invert exactly); every other
        resident-bank consumer restores it defensively first."""
        _, fold_usage = self._fold_fns()

        self._restore_nominees()
        n = self._dev_nodes
        try:
            req_d, pc_d = fold_usage(n["requested"], n["pod_count"], rows, vecs, cnt)
        except Exception:
            self._device_stale = True
            raise
        self._dev_nodes = {**n, "requested": req_d, "pod_count": pc_d}
        self._nominee_overlay = (rows, vecs, cnt)
        self._ship("fold", rows.nbytes + vecs.nbytes + cnt.nbytes)
        return self._dev_nodes

    # ktpu: hot-path; confined(driver) dispatch path
    def unfold_nominees(self) -> None:
        """Fold the nominee overlay back out (exact integer inverse)."""
        overlay = self._nominee_overlay
        if overlay is None:
            return
        _, fold_usage = self._fold_fns()

        rows, vecs, cnt = overlay
        self._nominee_overlay = None
        n = self._dev_nodes
        try:
            req_d, pc_d = fold_usage(n["requested"], n["pod_count"], rows, -vecs, -cnt)
        except Exception:
            self._device_stale = True
            raise
        self._dev_nodes = {**n, "requested": req_d, "pod_count": pc_d}
        self._ship("fold", rows.nbytes + vecs.nbytes + cnt.nbytes)

    # ktpu: confined(driver) driver-thread dispatch path
    def _restore_nominees(self) -> None:
        if self._nominee_overlay is not None:
            self.unfold_nominees()

    def device_bank_divergence(self) -> List[str]:
        """Names of device-resident arrays that are NOT bit-identical to
        the host banks (after dtype canonicalization — the upload path's
        own truncation). Empty list = the resident-state plane is exact.
        This is the parity probe the fold test suite and perf_smoke use;
        it fetches the full banks, so it is a debug/verification API, not
        a hot-path one. Fetches go through a DEVICE-SIDE COPY: np.asarray
        on the resident array itself would cache a host view on it
        (jax.Array._npy_value), and that cached reference silently blocks
        the NEXT fold's buffer donation — the probe must not perturb what
        it measures."""
        import jax.numpy as jnp

        self._restore_nominees()
        out: List[str] = []
        if self._dev_nodes is None:
            return out
        for label, dev, host in (
            ("nodes", self._dev_nodes, self.nodes.arrays()),
            ("eps", self._dev_eps, self.eps.arrays()),
            ("pats", self._dev_pats, self.pats.arrays()),
        ):
            for k, h in host.items():
                d = dev.get(k)
                if d is None:
                    out.append(f"{label}.{k}:missing")
                    continue
                dn = np.asarray(jnp.array(d, copy=True))
                if dn.shape != h.shape or not np.array_equal(
                    dn, np.asarray(h).astype(dn.dtype)
                ):
                    out.append(f"{label}.{k}")
        # columnar cross-check (state/columns.py): the cache's hot
        # columns vs the host bank's usage arrays — ONE vectorized
        # compare over gathered matrices, replacing the per-node object
        # walk a host-truth audit used to need. Only meaningful when the
        # mirror is fully synced (no outstanding deltas/dirt).
        cache = self.cache
        cols = getattr(cache, "_columns", None)
        if cols is not None and cols.vocab is self.vocab:
            # (vocab-mismatched columns — rebuilt by another scheduler —
            # are in a different slot order; comparing them here would
            # false-fire, and the delta paths already fell back)
            with cache._lock:
                if not cache.pod_deltas and not cache.dirty_nodes:
                    out.extend(
                        cols.usage_divergence_locked(self.row_of, self.nodes)
                    )
        return out

    def node_name_of_row(self, row: int) -> Optional[str]:
        if 0 <= row < len(self.name_of_row):
            return self.name_of_row[row]
        return None

    # ktpu: confined(driver) census of driver-confined bookkeeping — the
    # health monitor never calls this itself: the DRIVER publishes it at
    # the post-sync safe point (obs/introspect.HealthMonitor.driver_sync_
    # hook), the same confinement contract every other mirror entry point
    # lives by. Counters and metadata only; never reads device buffers.
    # The one sanctioned OFF-driver caller is introspect.census's
    # no-monitor /debug/ktpu fallback, which accepts an ADVISORY read:
    # every field is a single len()/int read (atomic, possibly torn as a
    # set) except the ledger copy below, which is retry-wrapped because
    # the UPLOADER threads add fresh ledger kinds concurrently even in
    # normal driver-thread use.
    def census(self) -> Dict:
        for _ in range(4):
            try:
                shipped = dict(self.bytes_shipped)
                break
            except RuntimeError:  # a writer added a kind mid-copy
                continue
        else:  # pragma: no cover - needs 4 adds of brand-new kinds mid-copy
            shipped = {}
        return {
            "node_capacity": int(self.nodes.capacity),
            "node_rows": len(self.row_of),
            "sig_capacity": int(self.eps.capacity),
            "sig_rows": len(self.eps._sig_of),
            "pattern_capacity": int(self.pats.capacity),
            "pattern_rows": len(self.pats._row_of),
            "device_resident": (
                self._dev_nodes is not None and not self._device_stale
            ),
            "pending_node_rows": len(self._pending_node_rows),
            "pending_usage_rows": len(self._pending_usage_rows),
            "pending_pat_rows": len(self._pending_pat_rows),
            "folded_usage_rows": len(self._folded_usage_rows),
            "folded_pat_rows": len(self._folded_pat_rows),
            "dirty_sig_rows": len(self.eps.dirty_sig_rows),
            "dirty_pattern_rows": len(self.pats.dirty_pattern_rows),
            "nominee_overlay": self._nominee_overlay is not None,
            "fold_count": int(self.fold_count),
            "folds_undonated": int(self.folds_undonated),
            "rebuild_count": int(self.rebuild_count),
            "generation": int(getattr(self, "generation", 0)),
            "device_generation": self.device_generation,
            "bytes_shipped": shipped,
        }


def _nbytes(v) -> int:
    a = np.asarray(v)
    return a.nbytes
