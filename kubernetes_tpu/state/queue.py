"""Scheduling queue: the 3-queue design of the reference.

Reference: pkg/scheduler/internal/queue/scheduling_queue.go PriorityQueue
(:120-152):
  activeQ         — heap ordered by (priority desc, enqueue time asc); pods
                    ready to schedule (Pop blocks on it, :444)
  podBackoffQ     — heap ordered by backoff expiry; pods that failed and are
                    waiting out their backoff (flushed to activeQ, :389)
  unschedulableQ  — map of pods that found no node; moved back to activeQ on
                    cluster events (MoveAllToActiveQueue :569) or after the
                    unschedulable timeout (:423, 60s)
plus the nominated-pods index (preemption nominees per node) and the
move-request cycle counter that closes the race between "pod determined
unschedulable" and "cluster changed meanwhile" (:353-386).

Backoff: PodBackoffMap (pod_backoff.go): initial 1s, doubled per attempt,
capped at 10s.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..analysis.lockorder import audited_condition
from ..api.types import Pod
from ..metrics import metrics as M
from ..obs import NOOP_SPAN, RECORDER as _REC

INITIAL_BACKOFF = 1.0  # pod_backoff.go initialDuration
MAX_BACKOFF = 10.0  # pod_backoff.go maxDuration
UNSCHEDULABLE_TIMEOUT = 60.0  # scheduling_queue.go unschedulableQTimeInterval


@dataclass
class PodInfo:
    """framework.PodInfo: pod + queue timestamps."""

    pod: Pod
    timestamp: float = 0.0  # time added to the queue
    attempts: int = 0
    seq: int = 0  # monotonic enqueue sequence (tie-break within priority)
    # per-pod latency attribution (kubernetes_tpu/obs): enqueue_ts is the
    # FIRST-admission stamp (survives requeue/unschedulable round-trips;
    # rebase_timestamps resets it with the rest), pop_ts the last pop —
    # both on the queue's own clock, read via age()/attempt_age() so
    # callers never mix clocks
    enqueue_ts: float = 0.0
    pop_ts: float = 0.0
    # pod-ingest plane (kubernetes_tpu/ingest): the entry's READY staged
    # row — encoded at admission on the informer thread, consumed by the
    # driver's index-only dispatch. (-1, -1) = not staged; a generation
    # mismatch at pop time means the row went stale (update/delete between
    # enqueue and pop, slab rebuild) and the pod re-stages or falls back
    # to the legacy in-batch encode, counted.
    staged_row: int = -1
    staged_gen: int = -1
    # term-bank plane (kubernetes_tpu/terms_plane): the entry's READY
    # interned term set — same admission-time encode and staleness
    # contract as (staged_row, staged_gen), for the batch TermBank side
    # of the dispatch. term_row is a term-slab ENTRY id (one per distinct
    # (spec, spread-selectors) pair), not a row index.
    term_row: int = -1
    term_gen: int = -1


class _ActiveEntry:
    """activeQ heap node for the QueueSort-plugin path. Default-ordered
    queues use plain (neg_prio, seq, key) TUPLES instead: tuple comparison
    is C-level, and at 100k pending pods the ~17 Python __lt__ calls per
    heappop were ~16us/pod of pure comparator overhead (the activeQ
    comparator itself is (priority desc, seq asc), scheduling_queue.go:120
    — identical either way)."""

    __slots__ = ("neg_prio", "seq", "key", "info", "less")

    def __init__(self, info: PodInfo, less):
        self.neg_prio = -info.pod.get_priority()
        self.seq = info.seq
        self.key = info.pod.key()
        self.info = info
        self.less = less

    def __lt__(self, other: "_ActiveEntry") -> bool:
        if self.less is not None:
            return bool(self.less(self.info, other.info))
        return (self.neg_prio, self.seq) < (other.neg_prio, other.seq)


def _entry_key(e) -> str:
    """Pod key of a heap entry in either representation."""
    return e[2] if type(e) is tuple else e.key


class PriorityQueue:
    def __init__(self, now: Callable[[], float] = time.monotonic, less=None):
        # lock role "queue": first in the queue → stage ordering (the
        # informer's admission path holds queue then acquires stage rows)
        self._lock = audited_condition("queue")
        self._now = now
        self._seq = itertools.count()
        self._less = less  # QueueSort plugin comparator (PodInfo, PodInfo) -> bool
        self._active: List[_ActiveEntry] = []  # ktpu: guarded-by(self._lock)
        self._backoff: List[Tuple[float, int, str]] = []  # ktpu: guarded-by(self._lock)
        self._unschedulable: Dict[str, PodInfo] = {}  # ktpu: guarded-by(self._lock)
        self._infos: Dict[str, PodInfo] = {}  # ktpu: guarded-by(self._lock)
        self._in_active: Set[str] = set()  # ktpu: guarded-by(self._lock)
        self._attempts: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        self._last_failure: Dict[str, float] = {}  # ktpu: guarded-by(self._lock)
        self._last_move_request_cycle = -1  # ktpu: guarded-by(self._lock)
        self._scheduling_cycle = 0  # ktpu: guarded-by(self._lock)
        self.nominated: Dict[str, str] = {}  # ktpu: guarded-by(self._lock)
        self._nominated_by_node: Dict[str, Set[str]] = {}  # ktpu: guarded-by(self._lock)
        # bumped whenever a NOMINATION IS ADDED (never on clears): the
        # driver folds outstanding nominations into the device mask at
        # dispatch, and a speculated solve is consumable only if no
        # nomination appeared since (clears only make the mask
        # conservative — safe)
        self.nomination_adds = 0  # ktpu: guarded-by(self._lock)
        self.closed = False
        # pod-ingest plane: when a PodStage is attached, admissions encode
        # the pod's tensor row HERE (the informer thread) instead of on
        # the driver thread per batch; entries carry the ready (row, gen)
        self._stage = None  # ktpu: guarded-by(self._lock)
        # term-bank plane: the term slab (terms_plane.TermStage) gets the
        # same admission-time treatment; entries carry (entry id, gen)
        self._tstage = None  # ktpu: guarded-by(self._lock)

    # -- admission-time staging (kubernetes_tpu/ingest + terms_plane) --------

    def attach_stage(self, stage) -> None:
        """Install the ingest plane's staging slab. Entries added before
        the attach are staged lazily (warmup census / dispatch restage).
        Lock order: queue lock → stage lock, always."""
        with self._lock:
            self._stage = stage

    def attach_term_stage(self, stage) -> None:
        """Install the term plane's slab (terms_plane.TermStage) — the
        same contract as attach_stage. Lock order: queue lock → terms
        lock, always."""
        with self._lock:
            self._tstage = stage

    # ktpu: holds(self._lock) the one definition of the attached staging
    # planes every acquire/release/swap helper iterates
    def _planes_locked(self):
        out = []
        if self._stage is not None:
            out.append((self._stage, "staged_row", "staged_gen"))
        if self._tstage is not None:
            out.append((self._tstage, "term_row", "term_gen"))
        return out

    @staticmethod
    def _plane_acquire(stage, info: PodInfo, row_attr: str, gen_attr: str) -> None:
        """Acquire one plane's pair for `info` and record it — the ONE
        place the (row, gen) attachment bookkeeping lives (admission and
        re-add/census paths both route through it)."""
        pair = stage.acquire(info.pod)
        if pair is None:
            setattr(info, row_attr, -1)
            setattr(info, gen_attr, -1)
        else:
            setattr(info, row_attr, pair[0])
            setattr(info, gen_attr, pair[1])

    # ktpu: holds(self._lock) called from locked admission/re-add paths
    def _stage_acquire(self, info: PodInfo) -> None:
        for stage, row_attr, gen_attr in self._planes_locked():
            self._plane_acquire(stage, info, row_attr, gen_attr)

    # ktpu: holds(self._lock) called from locked delete/re-add paths
    def _stage_release(self, info: Optional[PodInfo]) -> None:
        if info is None:
            return
        for stage, row_attr, gen_attr in self._planes_locked():
            row = getattr(info, row_attr)
            if row < 0:
                continue
            stage.release(row, getattr(info, gen_attr))
            setattr(info, row_attr, -1)
            setattr(info, gen_attr, -1)

    # ktpu: holds(self._lock) called from locked update path
    def _stage_swap(self, info: PodInfo, new: Pod) -> None:
        """Update an entry's pod and re-stage it, acquiring the NEW row
        before releasing the old: a content-identical update (status-only
        patch) is then an intern HIT on the same row — no re-encode, no
        generation churn — while a real spec change lands a different
        row and the old one frees (the staleness tag, by design)."""
        old = [
            (stage, getattr(info, row_attr), getattr(info, gen_attr))
            for stage, row_attr, gen_attr in self._planes_locked()
        ]
        info.pod = new
        self._stage_acquire(info)
        for stage, old_row, old_gen in old:
            if old_row >= 0:
                stage.release(old_row, old_gen)

    # ktpu: holds(self._lock) called from locked re-add/census paths
    def _stage_acquire_if_stale(self, info: PodInfo) -> None:
        """Re-acquire on the RE-ADD paths when the entry's pair is missing
        OR no longer valid (its row was freed/rebuilt while the entry was
        popped) — without this, a once-stale entry would re-stage at
        every subsequent dispatch, double-counting one staleness event."""
        for stage, row_attr, gen_attr in self._planes_locked():
            row = getattr(info, row_attr)
            if row >= 0 and stage.valid_pair(row, getattr(info, gen_attr)):
                continue
            self._plane_acquire(stage, info, row_attr, gen_attr)

    def stage_pending(self) -> int:
        """Stage every pending entry that lacks a valid pair — the warmup
        census's staging half, under the QUEUE lock so it cannot race the
        informer's delete()/update() release/acquire pairs (an unlocked
        acquire into a concurrently-deleted entry would pin its slab row
        forever). Returns the number of entries (re-)staged, counting
        each plane (pod rows and term entries) separately."""
        n = 0
        with self._lock:
            if not self._planes_locked():
                return 0
            for k in self._pending_keys_locked():
                info = self._infos.get(k)
                if info is None:
                    continue
                before = (info.staged_row, info.term_row)
                self._stage_acquire_if_stale(info)
                if info.staged_row >= 0 and info.staged_row != before[0]:
                    n += 1
                if info.term_row >= 0 and info.term_row != before[1]:
                    n += 1
        return n

    def _pending_keys_locked(self) -> Set[str]:
        """Keys of every entry currently PENDING (active + backoff +
        unschedulable). Lock held by the caller — the one definition the
        census walk and the staging walk both use."""
        keys = set(self._in_active)
        keys.update(k for _, _, k in self._backoff)
        keys.update(self._unschedulable)
        return keys

    def pending_infos(self) -> List[PodInfo]:
        """Every pending entry — the warmup census walks this to pre-size
        the signature/pattern banks and to stage the whole backlog, not
        just the peeked batch."""
        with self._lock:
            return [
                self._infos[k]
                for k in self._pending_keys_locked()
                if k in self._infos
            ]

    # -- internals -----------------------------------------------------------

    def set_queue_sort(self, less) -> None:
        """Install a QueueSort plugin comparator; re-sorts pending entries
        (switching the heap from the tuple to the _ActiveEntry
        representation when a comparator appears)."""
        with self._lock:
            entries = [self._infos[_entry_key(e)] for e in self._active]
            self._less = less
            if less is None:
                self._active = [(-i.pod.get_priority(), i.seq, i.pod.key()) for i in entries]
            else:
                self._active = [_ActiveEntry(i, less) for i in entries]
            heapq.heapify(self._active)

    # ktpu: holds(self._lock) every caller is a locked public method
    def _push_active(self, info: PodInfo) -> None:
        key = info.pod.key()
        self._infos[key] = info
        if key in self._in_active:
            return
        if self._less is None:
            heapq.heappush(
                self._active, (-info.pod.get_priority(), info.seq, key)
            )
        else:
            heapq.heappush(self._active, _ActiveEntry(info, self._less))
        self._in_active.add(key)
        self._lock.notify()

    # ktpu: holds(self._lock) every caller is a locked public method
    def _backoff_duration(self, key: str) -> float:
        attempts = self._attempts.get(key, 0)
        d = INITIAL_BACKOFF * (2 ** max(attempts - 1, 0))
        return min(d, MAX_BACKOFF)

    # -- public API (scheduling_queue.go) -----------------------------------

    @staticmethod
    def _warm_memos(pod: Pod) -> None:
        """Warm the pod's resource-request + spec-key memos off the critical
        path (enqueue runs on the informer thread or at setup) so the commit
        loop's assume path finds them hot; with_node clones carry them."""
        from ..oracle.nodeinfo import accumulated_request, pod_non_zero_request
        from .tensors import spec_key

        accumulated_request(pod)
        pod_non_zero_request(pod)
        pod.host_ports()
        spec_key(pod)

    def add(self, pod: Pod) -> None:
        """Add: new pending pod → activeQ."""
        self._warm_memos(pod)
        # stage OUTSIDE the queue lock (same reason _warm_memos is): the
        # row encode is the admission path's heavy half, and holding the
        # queue lock through it would stall the driver's pops during
        # admission bursts. The acquired ref keeps the row live until the
        # pair attaches below; a racing delete of the same key releases
        # the OLD entry's pair, never this one.
        # _stage/_tstage are attach-once before traffic; the acquired refs
        # make any race with a concurrent delete benign (doc above)
        stage = self._stage  # ktpu: allow(KTPU003) attach-once reference read
        tstage = self._tstage  # ktpu: allow(KTPU003) attach-once reference read
        if _REC.enabled:
            # flight recorder: the admission path's spans — the row/term
            # encodes (stage-encode, the heavy half, on THIS thread — the
            # informer in production) nested inside the enqueue span
            with _REC.span("enqueue", pod=pod.key()):
                with (_REC.span("stage-encode", pod=pod.key())
                      if stage is not None or tstage is not None
                      else NOOP_SPAN):
                    pair = stage.acquire(pod) if stage is not None else None
                    tpair = tstage.acquire(pod) if tstage is not None else None
        else:
            pair = stage.acquire(pod) if stage is not None else None
            tpair = tstage.acquire(pod) if tstage is not None else None
        with self._lock:
            now = self._now()
            prev = self._infos.get(pod.key())
            info = PodInfo(pod=pod, timestamp=now, seq=next(self._seq))
            # first-admission stamp survives re-adds of the same key (the
            # e2e attribution anchor); a re-created pod restarts it
            info.enqueue_ts = (
                prev.enqueue_ts if prev is not None and prev.enqueue_ts > 0
                else now
            )
            if pair is not None:
                info.staged_row, info.staged_gen = pair
            if tpair is not None:
                info.term_row, info.term_gen = tpair
            # attach-new-then-release-old: an identical re-add lands on
            # the same row as an intern hit (no re-encode, no generation
            # churn); real content changes free the old row normally
            self._stage_release(self._infos.get(pod.key()))
            self._unschedulable.pop(pod.key(), None)
            self._push_active(info)
            self._update_nominated(pod)

    def pop(self, timeout: Optional[float] = None) -> Optional[PodInfo]:
        """Pop: blocks until a pod is available (queue.Pop :444)."""
        with self._lock:
            deadline = None if timeout is None else self._now() + timeout
            while not self._active and not self.closed:
                self._flush_locked()
                wait = 0.1
                if deadline is not None:
                    remaining = deadline - self._now()
                    if remaining <= 0:
                        return None
                    wait = min(wait, remaining)
                self._lock.wait(wait)
            if self.closed and not self._active:
                return None
            key = _entry_key(heapq.heappop(self._active))
            self._in_active.discard(key)
            info = self._infos[key]
            info.attempts += 1
            info.pop_ts = self._now()
            self._scheduling_cycle += 1
        M.queue_incoming_wait.observe(max(info.pop_ts - info.timestamp, 0.0))
        return info

    def pop_batch(self, max_pods: int) -> List[PodInfo]:
        """Drain up to max_pods from activeQ without blocking — the batch
        entry point for the vectorized solver. Preserves pop order."""
        with self._lock:
            self._flush_locked()
            out = []
            pop = heapq.heappop
            active, in_active, infos = self._active, self._in_active, self._infos
            now = self._now()
            while active and len(out) < max_pods:
                key = _entry_key(pop(active))
                in_active.discard(key)
                info = infos[key]
                info.attempts += 1
                info.pop_ts = now
                out.append(info)
            if out:
                self._scheduling_cycle += 1
        if out:
            # queue-wait attribution: one bulk observe per batch (outside
            # the queue lock — the histogram has its own)
            M.queue_incoming_wait.observe_many(
                [max(now - i.timestamp, 0.0) for i in out]
            )
        return out

    def rebase_timestamps(self) -> int:
        """Reset every queued entry's enqueue timestamp to NOW. Harnesses
        that enqueue before a warmup phase call this at warmup end so
        age()/PodSchedulingDuration measure scheduling, not setup — the
        round-5 verdict's warmup-polluted p50/p99. Returns the number of
        entries rebased."""
        with self._lock:
            now = self._now()
            for info in self._infos.values():
                info.timestamp = now
                info.enqueue_ts = now
            for info in self._unschedulable.values():
                info.timestamp = now
                info.enqueue_ts = now
            return len(self._infos) + len(self._unschedulable)

    def requeue(self, infos: Sequence[PodInfo]) -> None:
        """Return popped-but-uncommitted pods to activeQ, preserving their
        enqueue seq and timestamp — the commit plane's defer-to-next-batch
        verdict. Unlike add_unschedulable this applies NO backoff: the pod
        was not unschedulable, it merely conflicted with an earlier commit
        of its own batch and must re-solve against the committed state."""
        with self._lock:
            for info in infos:
                self._stage_acquire_if_stale(info)
                self._unschedulable.pop(info.pod.key(), None)
                self._push_active(info)

    def peek_batch(self, max_pods: int) -> List[PodInfo]:
        """Up to max_pods PodInfos visible in activeQ WITHOUT popping (heap
        order prefix, not sorted). The driver's warmup uses this to trace,
        compile, and upload at the real workload's shapes and term kinds
        before the first scheduling cycle."""
        with self._lock:
            out = []
            for e in self._active[:max_pods]:
                info = self._infos.get(_entry_key(e))
                if info is not None:
                    out.append(info)
            return out

    def pop_all_in_groups(self, groups, group_fn) -> List[PodInfo]:
        """Drain EVERY queued pod whose group_fn(pod) is in `groups`,
        regardless of batch size — gang groups must be decided atomically,
        so a batch containing any member pulls in all queued members
        (otherwise a group straddling the batch boundary would have its
        first slice bound before the rest was ever considered)."""
        with self._lock:
            take = [
                e for e in self._active
                if group_fn(self._infos[_entry_key(e)].pod) in groups
            ]
            if not take:
                return []
            taken_keys = {_entry_key(e) for e in take}
            self._active = [e for e in self._active if _entry_key(e) not in taken_keys]
            heapq.heapify(self._active)
            out = []
            now = self._now()
            for e in sorted(take):
                key = _entry_key(e)
                self._in_active.discard(key)
                info = self._infos[key]
                info.attempts += 1
                info.pop_ts = now
                out.append(info)
        M.queue_incoming_wait.observe_many(
            [max(now - i.timestamp, 0.0) for i in out]
        )
        return out

    def add_unschedulable(self, info: PodInfo, pod_scheduling_cycle: Optional[int] = None) -> None:
        """AddUnschedulableIfNotPresent (:353): if a move request arrived
        since this pod's cycle started, go to backoffQ (retry soon) instead
        of unschedulableQ (wait for an event)."""
        with self._lock:
            key = info.pod.key()
            self._stage_acquire_if_stale(info)
            self._attempts[key] = self._attempts.get(key, 0) + 1
            self._last_failure[key] = self._now()
            cycle = pod_scheduling_cycle if pod_scheduling_cycle is not None else self._scheduling_cycle
            if self._last_move_request_cycle >= cycle:
                expiry = self._now() + self._backoff_duration(key)
                self._infos[key] = info
                heapq.heappush(self._backoff, (expiry, info.seq, key))
            else:
                info.timestamp = self._now()
                self._infos[key] = info
                self._unschedulable[key] = info
            self._update_nominated(info.pod)

    def requeue_backoff(self, info: PodInfo) -> None:
        """Bind/RPC-failure requeue: ALWAYS the backoff tier with per-pod
        exponential backoff (1s → 10s, pod_backoff.go DefaultPodBackoff),
        never unschedulableQ. A bind failure is not unschedulability —
        the pod had a node; re-adding it immediately (the old forget +
        requeue path) retries a possibly-still-broken binder in a hot
        loop, while parking it in unschedulableQ makes it wait for a
        cluster event that may never come. The attempt count (and so the
        backoff) resets through clear_backoff like every other failure."""
        with self._lock:
            key = info.pod.key()
            self._stage_acquire_if_stale(info)
            self._attempts[key] = self._attempts.get(key, 0) + 1
            self._last_failure[key] = self._now()
            self._unschedulable.pop(key, None)
            self._infos[key] = info
            expiry = self._now() + self._backoff_duration(key)
            heapq.heappush(self._backoff, (expiry, info.seq, key))
            self._update_nominated(info.pod)
            # wake blocked poppers so they flush the backoff heap when due
            self._lock.notify()

    def scheduling_cycle(self) -> int:
        with self._lock:
            return self._scheduling_cycle

    def move_all_to_active(self) -> None:
        """MoveAllToActiveQueue (:569): a cluster event may have made
        unschedulable pods feasible."""
        with self._lock:
            now = self._now()
            for key, info in list(self._unschedulable.items()):
                # still backing off → backoffQ; else straight to activeQ
                expiry = self._last_failure.get(key, 0.0) + self._backoff_duration(key)
                if expiry <= now:
                    self._push_active(info)
                else:
                    heapq.heappush(self._backoff, (expiry, info.seq, key))
            self._unschedulable.clear()
            self._last_move_request_cycle = self._scheduling_cycle
            self._lock.notify_all()

    def _flush_locked(self) -> None:
        """flushBackoffQCompleted (:389) + flushUnschedulableQLeftover
        (:423)."""
        now = self._now()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            info = self._infos.get(key)
            if info is not None:
                self._push_active(info)
        for key, info in list(self._unschedulable.items()):
            if now - info.timestamp > UNSCHEDULABLE_TIMEOUT:
                del self._unschedulable[key]
                self._push_active(info)

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def delete(self, pod: Pod) -> None:
        with self._lock:
            key = pod.key()
            # ingest plane: the entry's staged row loses this holder; if it
            # was the last, the row frees and any popped-but-undispatched
            # copy of the entry sees the generation mismatch (the
            # delete-between-enqueue-and-pop staleness, by design)
            self._stage_release(self._infos.get(key))
            self._infos.pop(key, None)
            self._unschedulable.pop(key, None)
            self._in_active.discard(key)  # lazily skipped on pop
            self._attempts.pop(key, None)
            self._last_failure.pop(key, None)
            self._remove_nominated(key)
            self._active = [e for e in self._active if _entry_key(e) != key]
            heapq.heapify(self._active)
            # purge the backoff heap too: stale entries would otherwise be
            # counted by counts() (pending_pods gauge) until expiry
            if any(k == key for _, _, k in self._backoff):
                self._backoff = [t for t in self._backoff if t[2] != key]
                heapq.heapify(self._backoff)

    def update(self, old: Pod, new: Pod) -> None:
        self._warm_memos(new)  # fresh object: same critical-path concern as add
        with self._lock:
            key = new.key()
            if key in self._unschedulable:
                info = self._unschedulable.pop(key)
                self._stage_swap(info, new)
                self._push_active(info)
            elif key in self._infos:
                self._stage_swap(self._infos[key], new)
            else:
                self.add(new)
            self._update_nominated(new)

    def clear_backoff(self, pod: Pod) -> None:
        with self._lock:
            self._attempts.pop(pod.key(), None)
            self._last_failure.pop(pod.key(), None)

    # -- nominated pods (preemption nominees) --------------------------------

    # ktpu: holds(self._lock) every caller is a locked public method
    def _update_nominated(self, pod: Pod) -> None:
        key = pod.key()
        self._remove_nominated(key)
        node = pod.nominated_node_name
        if node:
            self.nominated[key] = node
            self._nominated_by_node.setdefault(node, set()).add(key)
            self.nomination_adds += 1

    # ktpu: holds(self._lock) every caller is a locked public method
    def _remove_nominated(self, key: str) -> None:
        node = self.nominated.pop(key, None)
        if node:
            self._nominated_by_node.get(node, set()).discard(key)

    def clear_nomination(self, key: str) -> None:
        """Drop a pending pod's nomination (the preempt 'clear' list,
        generic_scheduler.go:346-360: lower-priority nominees of a node just
        claimed by a higher-priority preemptor)."""
        with self._lock:
            self._remove_nominated(key)
            info = self._infos.get(key)
            if info is not None:
                info.pod.nominated_node_name = ""

    def nomination_extras(self, exclude_keys) -> List[Tuple[str, Pod]]:
        """Outstanding (node, pod) nominations EXCLUDING the given keys —
        the driver folds these into the device mask at dispatch (the
        podFitsOnNode pass-1 nominee accounting, generic_scheduler.go:
        620-630, batched: in-batch nominees are covered by the solver's
        own sequential carry, so only out-of-batch ones are listed)."""
        with self._lock:
            return [
                (node, self._infos[k].pod)
                for k, node in self.nominated.items()
                if k not in exclude_keys and k in self._infos
            ]

    def clear_nominations(self, keys) -> None:
        """Bulk clear_nomination under one lock (the bulk-commit fast
        path's per-batch nomination drop)."""
        with self._lock:
            for key in keys:
                self._remove_nominated(key)
                info = self._infos.get(key)
                if info is not None:
                    info.pod.nominated_node_name = ""

    def has_nominations(self) -> bool:
        """True if ANY pod currently nominates a node (empty sets left by
        discard don't count). Batch drivers use this to skip the per-pod
        nominated lookup entirely when the index is empty."""
        with self._lock:
            return any(self._nominated_by_node.values())

    def nominated_pods_for_node(self, node: str) -> List[Pod]:
        with self._lock:
            return [
                self._infos[k].pod
                for k in self._nominated_by_node.get(node, set())
                if k in self._infos
            ]

    def close(self) -> None:
        with self._lock:
            self.closed = True
            self._lock.notify_all()

    def pending_count(self) -> int:
        with self._lock:
            return len(self._active) + len(self._backoff) + len(self._unschedulable)

    def age(self, info: PodInfo) -> float:
        """Seconds since the pod was (re-)queued, on THIS queue's clock —
        callers must not mix their own clock with info.timestamp."""
        return self._now() - info.timestamp

    def attempt_age(self, info: PodInfo) -> float:
        """Seconds since the entry was last POPPED (this attempt's wall so
        far), on the queue's clock; 0.0 for a never-popped entry — the
        scheduling_attempt_duration observation the commit/fail paths
        record per pod."""
        if info.pop_ts <= 0.0:
            return 0.0
        return max(self._now() - info.pop_ts, 0.0)

    def counts(self) -> Tuple[int, int, int]:
        """(active, backoff, unschedulable) — the pending_pods gauge split."""
        with self._lock:
            return len(self._active), len(self._backoff), len(self._unschedulable)

    # ktpu: holds(self._lock) min-timestamp walk over the pending set
    def _oldest_pending_ts_locked(self) -> Optional[float]:
        oldest = None
        for k in self._pending_keys_locked():
            info = self._infos.get(k)
            if info is not None and (oldest is None or info.timestamp < oldest):
                oldest = info.timestamp
        return oldest

    def oldest_pending_age(self) -> float:
        """Age of the OLDEST pending entry, on the queue's OWN clock (the
        age()/attempt_age() discipline — callers never mix clocks). The
        lock covers only the min-timestamp walk; the gauge observation
        the driver/health monitor makes from this value happens outside
        it. 0.0 when nothing is pending."""
        with self._lock:
            now = self._now()
            oldest = self._oldest_pending_ts_locked()
        if oldest is None:
            return 0.0
        return max(now - oldest, 0.0)

    def census(self) -> Dict:
        """One lock-disciplined snapshot of the queue's steady-state
        health (obs/introspect): pending depth by sub-queue, the oldest
        pending entry's age on the queue's clock, and the nomination
        index size. Counters and metadata only — the monitor's
        no-forcing contract starts here."""
        with self._lock:
            now = self._now()
            oldest = self._oldest_pending_ts_locked()
            return {
                "active": len(self._active),
                "backoff": len(self._backoff),
                "unschedulable": len(self._unschedulable),
                "oldest_pending_age_s": (
                    max(now - oldest, 0.0) if oldest is not None else 0.0
                ),
                "nominated": len(self.nominated),
                "scheduling_cycle": self._scheduling_cycle,
                "closed": self.closed,
            }
