"""String interning: the device-side representation of label strings.

The reference matches label strings directly (labels.Set, predicates.go:979
and friends). On TPU, strings can't live in kernels, so every distinct string
(label key, "key=value" pair, taint triple, image name, topology value...)
is assigned a dense int32 id by this interner. Matching becomes exact integer
equality — no hash collisions by construction, unlike feature hashing.

Id 0 is reserved as ABSENT/padding everywhere; real ids start at 1. The
interner only grows; ids are stable for the life of the process, so device
tensors patched incrementally across events never need re-encoding.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..analysis.lockorder import audited_lock

ABSENT = 0


class StringInterner:
    def __init__(self) -> None:
        self._lock = audited_lock("interner")
        self._to_id: Dict[str, int] = {}  # ktpu: guarded-by(self._lock)
        self._from_id: List[Optional[str]] = [None]  # index 0 = ABSENT

    def intern(self, s: str) -> int:
        with self._lock:
            i = self._to_id.get(s)
            if i is None:
                i = len(self._from_id)
                self._to_id[s] = i
                self._from_id.append(s)
            return i

    def intern_kv(self, key: str, value: str) -> int:
        # \x00 cannot appear in valid label keys/values, so this is injective.
        return self.intern(key + "\x00" + value)

    def lookup(self, s: str) -> int:
        """Like intern but read-only: unknown string -> ABSENT."""
        with self._lock:  # read path locked like the vocab slot maps (PR 6)
            return self._to_id.get(s, ABSENT)

    def lookup_kv(self, key: str, value: str) -> int:
        with self._lock:
            return self._to_id.get(key + "\x00" + value, ABSENT)

    def intern_all(self, strs: Iterable[str]) -> List[int]:
        return [self.intern(s) for s in strs]

    def string(self, i: int) -> Optional[str]:
        if 0 <= i < len(self._from_id):
            return self._from_id[i]
        return None

    def __len__(self) -> int:
        return len(self._from_id) - 1
