"""Tensorization layer: cluster state → fixed-capacity device tensors.

The TPU-native replacement for the scheduler cache snapshot
(pkg/scheduler/internal/cache/cache.go UpdateNodeInfoSnapshot, nodeinfo/
snapshot.go): instead of a map of NodeInfo structs walked per pod, cluster
state is encoded once into padded, statically-shaped integer tensors that the
vectorized Filter/Score kernels (kubernetes_tpu/ops) evaluate for a whole
pod batch at once.

Encoding scheme
---------------
* Every string (label key, label value, taint key/value, namespace, node
  name, image name, protocol, host IP) is interned to a dense int32 id
  (state/interner.py); id 0 = ABSENT/padding. Matching is exact integer
  equality — no hash collisions.
* Label KEYS additionally get a dense "key slot" in [0, K): node and pod
  labels become a K-wide value-id row (`label_vals[i, slot]`), so a selector
  requirement compiles to (slot, op, value-id-set) and evaluates as a
  vectorized compare against the whole node axis. Cluster-wide distinct
  label keys are few (zone/region/hostname/app/env/...), so K stays small;
  overflow grows K to the next bucket and re-encodes (bounded recompiles).
* Numeric label values are pre-parsed into a parallel int64 plane for the
  Gt/Lt node-affinity operators (labels.Requirement ParseInt64 semantics).
* Resources get dense slots: 0=cpu(milli) 1=memory(bytes) 2=ephemeral
  3..=extended/scalar resources as first seen.
* Variable-length structures (taints, tolerations, selector terms, ports)
  are padded to per-structure capacities with a validity mask. A pod whose
  structures exceed capacity sets `fallback` — the driver schedules it via
  the scalar oracle path instead (capacity is sized so this is rare).

All arrays are built host-side in numpy (cheap incremental row writes) and
shipped to device per scheduling cycle; dtype discipline: int32 ids/slots,
int64 resource quantities, bool masks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..analysis.lockorder import audited_lock
from ..api.types import (
    Node,
    NodeSelectorRequirement,
    Pod,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE,
)
from ..oracle.nodeinfo import (
    DEFAULT_BIND_ALL_HOST_IP,
    LABEL_ZONE_FAILURE_DOMAIN,
    LABEL_ZONE_REGION,
    NodeInfo,
    Snapshot,
    accumulated_request,
    normalized_image_name,
    pod_non_zero_request,
)
from ..oracle.priorities import (
    PREFER_AVOID_PODS_ANNOTATION,
    _pod_resource_limits,
    _pod_scoring_request,
)
from .interner import ABSENT, StringInterner

# --- operator codes for compiled node-selector requirements -----------------
OP_PAD = 0
OP_IN = 1
OP_NOT_IN = 2
OP_EXISTS = 3
OP_DOES_NOT_EXIST = 4
OP_GT = 5
OP_LT = 6
OP_NAME_IN = 7  # matchFields metadata.name In
OP_NAME_NOT_IN = 8  # matchFields metadata.name NotIn
OP_NEVER = 9  # compile-time-known unsatisfiable requirement

# --- taint effects ----------------------------------------------------------
EFFECT_PAD = 0
EFFECT_NO_SCHEDULE = 1
EFFECT_PREFER_NO_SCHEDULE = 2
EFFECT_NO_EXECUTE = 3
_EFFECT_CODE = {
    TAINT_NO_SCHEDULE: EFFECT_NO_SCHEDULE,
    TAINT_PREFER_NO_SCHEDULE: EFFECT_PREFER_NO_SCHEDULE,
    TAINT_NO_EXECUTE: EFFECT_NO_EXECUTE,
}

# toleration operators
TOL_EQUAL = 0
TOL_EXISTS = 1


@dataclass
class EncodingConfig:
    """Capacities for the padded encodings. Defaults sized for scheduler_perf
    style workloads; any overflow is handled (K grows; per-pod structures set
    the fallback flag)."""

    key_slots: int = 64  # K: distinct label keys cluster-wide
    resource_slots: int = 8  # R: cpu, mem, ephemeral + extended
    node_taints: int = 8  # T per node
    pod_tolerations: int = 8  # TL per pod
    nsel_terms: int = 4  # ORed required node-selector terms per pod
    nsel_reqs: int = 6  # ANDed requirements per term
    nsel_vals: int = 8  # value set size per requirement
    pref_terms: int = 4  # preferred node-affinity terms per pod
    node_ports: int = 32  # used host ports per node
    pod_ports: int = 8  # host ports per pod
    avoid_entries: int = 2  # preferAvoidPods signatures per node
    pod_images: int = 4  # containers (images) per pod

    # resource slot indices (fixed)
    CPU: int = 0
    MEM: int = 1
    EPHEMERAL: int = 2


class Vocab:
    """Interner + dense label-key-slot and resource-slot assignment shared by
    all encoders. Ids and slots are stable for the process lifetime so
    incrementally patched tensors never need re-encoding (interner.py)."""

    def __init__(self, config: Optional[EncodingConfig] = None):
        self.config = config or EncodingConfig()
        self.strings = StringInterner()
        self.key_slot: Dict[str, int] = {}  # ktpu: guarded-by(self._slot_lock)
        # ktpu: guarded-by(self._slot_lock)
        self.resource_slot: Dict[str, int] = {
            RESOURCE_CPU: self.config.CPU,
            RESOURCE_MEMORY: self.config.MEM,
            RESOURCE_EPHEMERAL_STORAGE: self.config.EPHEMERAL,
        }
        # interned constants used by kernels
        self.wildcard_ip = self.strings.intern(DEFAULT_BIND_ALL_HOST_IP)
        self.proto_tcp = self.strings.intern("TCP")
        self._dense: Dict[int, Dict[int, int]] = {}  # ktpu: guarded-by(self._slot_lock)
        self._zone_dense: Dict[int, int] = {}  # ktpu: guarded-by(self._slot_lock)
        # slot/dense assignment is a read-modify-write (len → insert): with
        # the pod-ingest plane, encodes run on the INFORMER thread too
        # (stage.acquire → set_pod) concurrently with the driver thread's
        # batch/node encodes — unlocked, two new keys could be assigned
        # the SAME slot, silently corrupting label matching forever. The
        # string interner has its own lock already. Readers take the lock
        # too (uncontended acquire is ~100ns; KTPU003 keeps the discipline
        # uniform instead of case-by-case GIL-atomicity arguments).
        self._slot_lock = audited_lock("vocab-slots")

    def zone_dense_of(self, zone_id: int) -> int:
        with self._slot_lock:
            idx = self._zone_dense.get(zone_id)
            if idx is None:
                idx = len(self._zone_dense)
                self._zone_dense[zone_id] = idx
            return idx

    # -- label keys → dense slots -------------------------------------------
    def slot_of_key(self, key: str) -> int:
        with self._slot_lock:
            s = self.key_slot.get(key)
            if s is None:
                s = len(self.key_slot)
                if s >= self.config.key_slots:
                    # grow bucket: next power of two; callers re-encode banks
                    self.config.key_slots *= 2
                self.key_slot[key] = s
            return s

    def peek_slot(self, key: str) -> int:
        """-1 when the key has never been seen (matches nothing)."""
        with self._slot_lock:
            return self.key_slot.get(key, -1)

    def slot_of_resource(self, name: str) -> int:
        with self._slot_lock:
            s = self.resource_slot.get(name)
            if s is None:
                s = len(self.resource_slot)
                if s >= self.config.resource_slots:
                    self.config.resource_slots *= 2
                self.resource_slot[name] = s
            return s

    def id(self, s: str) -> int:
        return self.strings.intern(s)

    # -- per-key-slot dense value indices (topology buckets) ----------------
    # For segment_sum/gather aggregation by topology value, each (key slot,
    # value id) pair gets a dense index in [0, N_values_of_slot). Stable and
    # grow-only like everything else.
    def dense_of(self, slot: int, val_id: int) -> int:
        with self._slot_lock:
            table = self._dense.setdefault(slot, {})
            idx = table.get(val_id)
            if idx is None:
                idx = len(table)
                table[val_id] = idx
            return idx

    def dense_size(self, slot: int) -> int:
        """Distinct dense values assigned for a key slot (upper bound on its
        dense indices). The topology kernels' segment axis only needs this
        many buckets FOR TERMS ON THIS SLOT — zone-keyed terms need ~#zones
        buckets, not one per node row (ops/pipeline n_buckets)."""
        with self._slot_lock:
            return len(self._dense.get(slot, ()))

    def zone_count(self) -> int:
        with self._slot_lock:
            return len(self._zone_dense)


def _parse_int_label(v: str) -> Tuple[int, bool]:
    """labels.Requirement Gt/Lt parse: base-10 int64 or no match."""
    try:
        return int(v, 10), True
    except ValueError:
        return 0, False


def spec_key(pod, selectors=None):
    """Canonical key of everything that shapes a pod's device mask/score
    row and compiled terms (PodBatch.set_pod + terms.compile_batch_terms
    inputs). Pods sharing a key — every replica of a controller — share ONE
    row of the [U, N] mask/score matrices; per-pod state (priority, queue
    order, gang group, volumes) stays on the batch axis.

    Containers/init-containers/overhead enter the row ONLY through their
    derived features (GetResourceRequest, scoring/limit requests, host
    ports, image names — everything set_pod reads), so the key hashes those
    derivations instead of repr()ing the container dataclasses: ~12us/pod
    of pure repr became ~1us, and the result is memoized on the pod (specs
    are immutable; updates arrive as new objects — same contract as the
    request memos). Complex substructures (tolerations, affinity, spread)
    still key by value-based dataclass repr."""
    if selectors is None:
        memo = pod.__dict__.get("_spec_key_memo")
        if memo is not None:
            return memo
    key = (
        pod.namespace,
        tuple(sorted(pod.labels.items())),
        pod.node_name,
        tuple(sorted(pod.resource_request().items())),
        _pod_scoring_request(pod),
        _pod_resource_limits(pod),
        tuple(pod.host_ports()),
        tuple(c.image for c in pod.containers),
        repr(pod.tolerations),
        tuple(sorted(pod.node_selector.items())),
        repr(pod.affinity),
        repr(pod.topology_spread_constraints),
        repr([r for r in pod.owner_references if r.get("controller")]),
        repr(selectors) if selectors is not None else None,
    )
    if selectors is None:
        pod.__dict__["_spec_key_memo"] = key
    return key


def _req_slot_pairs(vocab: "Vocab", pod) -> Tuple[Tuple[int, int], ...]:
    """accumulated_request as ((resource slot, value), ...) pairs, memoized
    on the pod (resource slots are grow-only and process-stable per Vocab,
    so cached slots never go stale; the memo is tagged with its vocab for
    test isolation). with_node clones carry it."""
    memo = pod.__dict__.get("_req_slot_memo")
    if memo is not None and memo[0] is vocab:
        return memo[1]
    pairs = tuple(
        (vocab.slot_of_resource(name), v)
        for name, v in accumulated_request(pod).items()
        if name != RESOURCE_PODS
    )
    pod.__dict__["_req_slot_memo"] = (vocab, pairs)
    return pairs


# ---------------------------------------------------------------------------
# Node bank
# ---------------------------------------------------------------------------

@dataclass
class NodeBank:
    """Padded per-node tensors, capacity N (= _node_bucket ≥ cluster size:
    power of two up to 2048, multiple of 2048 above). The device-side
    mirror of the scheduler cache's NodeInfo list."""

    vocab: Vocab
    capacity: int

    valid: np.ndarray = None  # [N] bool
    fallback: np.ndarray = None  # [N] bool: structures truncated; device path
    # must treat the node conservatively (excluded from fast-path placement)
    name_id: np.ndarray = None  # [N] int32
    alloc: np.ndarray = None  # [N, R] int64 (slot 〈pods〉 kept separately)
    requested: np.ndarray = None  # [N, R] int64 accumulated (calculateResource)
    nonzero_req: np.ndarray = None  # [N, 2] int64 (cpu milli, mem bytes) for scoring
    allowed_pods: np.ndarray = None  # [N] int32
    pod_count: np.ndarray = None  # [N] int32
    label_vals: np.ndarray = None  # [N, K] int32 value id (ABSENT=0)
    label_num: np.ndarray = None  # [N, K] int64 parsed numeric value
    label_num_ok: np.ndarray = None  # [N, K] bool
    taint_key: np.ndarray = None  # [N, T] int32
    taint_val: np.ndarray = None  # [N, T] int32
    taint_effect: np.ndarray = None  # [N, T] int32 (EFFECT_*)
    unschedulable: np.ndarray = None  # [N] bool
    port_proto: np.ndarray = None  # [N, P] int32
    port_ip: np.ndarray = None  # [N, P] int32
    port_num: np.ndarray = None  # [N, P] int32 (0 = pad)
    label_dense: np.ndarray = None  # [N, K] int32 dense topo bucket (-1 absent)
    zone_id: np.ndarray = None  # [N] int32 (GetZoneKey interned, 0 = none)
    zone_dense: np.ndarray = None  # [N] int32 dense zone bucket (-1 none)
    avoid_kind: np.ndarray = None  # [N, AV] int32 (1=RC, 2=RS)
    avoid_uid: np.ndarray = None  # [N, AV] int32
    image_scaled: np.ndarray = None  # [N, V_img] int64, see ImageTable

    def __post_init__(self):
        c = self.vocab.config
        self.key_capacity = c.key_slots  # array width; vocab may grow later
        n = self.capacity
        self.valid = np.zeros(n, bool)
        self.fallback = np.zeros(n, bool)
        self.name_id = np.zeros(n, np.int32)
        self.alloc = np.zeros((n, c.resource_slots), np.int64)
        self.requested = np.zeros((n, c.resource_slots), np.int64)
        self.nonzero_req = np.zeros((n, 2), np.int64)
        self.allowed_pods = np.zeros(n, np.int32)
        self.pod_count = np.zeros(n, np.int32)
        self.label_vals = np.zeros((n, c.key_slots), np.int32)
        self.label_num = np.zeros((n, c.key_slots), np.int64)
        self.label_num_ok = np.zeros((n, c.key_slots), bool)
        self.taint_key = np.zeros((n, c.node_taints), np.int32)
        self.taint_val = np.zeros((n, c.node_taints), np.int32)
        self.taint_effect = np.zeros((n, c.node_taints), np.int32)
        self.unschedulable = np.zeros(n, bool)
        self.port_proto = np.zeros((n, c.node_ports), np.int32)
        self.port_ip = np.zeros((n, c.node_ports), np.int32)
        self.port_num = np.zeros((n, c.node_ports), np.int32)
        self.label_dense = np.full((n, c.key_slots), -1, np.int32)
        self.zone_id = np.zeros(n, np.int32)
        self.zone_dense = np.full(n, -1, np.int32)
        self.avoid_kind = np.zeros((n, c.avoid_entries), np.int32)
        self.avoid_uid = np.zeros((n, c.avoid_entries), np.int32)
        self.image_scaled = None  # set by ImageTable.apply

    def set_node(self, i: int, ni: NodeInfo) -> None:
        """Encode one NodeInfo into row i (the patch path: called per dirty
        node, mirroring UpdateNodeInfoSnapshot's generation walk)."""
        v = self.vocab
        c = v.config
        node = ni.node
        self.valid[i] = True
        overflow = False
        self.name_id[i] = v.id(node.name)
        # resources
        self.alloc[i] = 0
        for name, amount in node.allocatable_int().items():
            if name == RESOURCE_PODS:
                self.allowed_pods[i] = amount
            else:
                s = v.slot_of_resource(name)
                if s >= self.alloc.shape[1]:
                    raise KeySlotOverflow()
                self.alloc[i, s] = amount
        self.requested[i] = 0
        for name, amount in ni.requested().items():
            if name != RESOURCE_PODS:
                s = v.slot_of_resource(name)
                if s >= self.requested.shape[1]:
                    raise KeySlotOverflow()
                self.requested[i, s] = amount
        nz_cpu, nz_mem = ni.non_zero_requested()
        self.nonzero_req[i, 0] = nz_cpu
        self.nonzero_req[i, 1] = nz_mem
        self.pod_count[i] = len(ni.pods)
        # labels
        self.label_vals[i] = ABSENT
        self.label_num_ok[i] = False
        self.label_dense[i] = -1
        for k, val in node.labels.items():
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            vid = v.id(val)
            self.label_vals[i, s] = vid
            self.label_dense[i, s] = v.dense_of(s, vid)
            num, ok = _parse_int_label(val)
            self.label_num[i, s] = num
            self.label_num_ok[i, s] = ok
        # taints
        self.taint_key[i] = 0
        self.taint_val[i] = 0
        self.taint_effect[i] = EFFECT_PAD
        if len(node.taints) > c.node_taints:
            overflow = True
        for t_idx, taint in enumerate(node.taints[: c.node_taints]):
            self.taint_key[i, t_idx] = v.id(taint.key)
            self.taint_val[i, t_idx] = v.id(taint.value)
            self.taint_effect[i, t_idx] = _EFFECT_CODE.get(taint.effect, EFFECT_PAD)
        self.unschedulable[i] = node.unschedulable
        # used host ports
        self.port_proto[i] = 0
        self.port_ip[i] = 0
        self.port_num[i] = 0
        used_ports = sorted(ni.used_host_ports())
        if len(used_ports) > c.node_ports:
            overflow = True
        for p_idx, (proto, ip, port) in enumerate(used_ports[: c.node_ports]):
            self.port_proto[i, p_idx] = v.id(proto)
            self.port_ip[i, p_idx] = v.id(ip)
            self.port_num[i, p_idx] = port
        # zone
        zone_key = _zone_key(node.labels)
        self.zone_id[i] = v.id(zone_key) if zone_key else ABSENT
        self.zone_dense[i] = v.zone_dense_of(self.zone_id[i]) if zone_key else -1
        # preferAvoidPods
        self.avoid_kind[i] = 0
        self.avoid_uid[i] = 0
        sigs = _avoid_signatures(node)
        if len(sigs) > c.avoid_entries:
            overflow = True
        for a_idx, (kind, uid) in enumerate(sigs[: c.avoid_entries]):
            self.avoid_kind[i, a_idx] = kind
            self.avoid_uid[i, a_idx] = v.id(uid)
        self.fallback[i] = overflow

    def clear_node(self, i: int) -> None:
        self.valid[i] = False
        self.pod_count[i] = 0
        # un-latch the conservative flags: a stale True on an invalid row
        # would force the driver's O(nodes) oracle fallback forever
        self.fallback[i] = False

    def apply_pod_delta(self, i: int, pod, sign: int) -> None:
        """Increment the pod-driven usage columns by one pod's request
        vector (the mirror's delta path) — numerically identical to the
        snapshot refresh because NodeInfo's own accounting added the exact
        same memoized values. Ports are NOT handled here (list-shaped —
        the caller snapshot-refreshes ported nodes)."""
        for rname, v in accumulated_request(pod).items():
            if rname != RESOURCE_PODS:
                s = self.vocab.slot_of_resource(rname)
                if s >= self.requested.shape[1]:
                    raise KeySlotOverflow()
                self.requested[i, s] += sign * v
        c, m = pod_non_zero_request(pod)
        self.nonzero_req[i, 0] += sign * c
        self.nonzero_req[i, 1] += sign * m
        self.pod_count[i] += sign

    def apply_pod_deltas_bulk(
        self, rows: np.ndarray, pods: Sequence, mats=None
    ) -> None:
        """apply_pod_delta over a whole commit batch of ADDS as three
        np.add.at scatters (duplicate rows accumulate). The per-pod numpy
        scalar `+=` of the scalar path was ~8us/pod at 4096-pod batches —
        the single biggest slice of mirror sync. Exactness unchanged: the
        same memoized request values land in the same columns. `mats`,
        when given, is the pre-gathered (req[B, R], nz[B, 2]) pair from
        the columnar cache's interned spec rows (state/columns.py) — the
        one-delta-source fast path that skips the per-pod build below."""
        if mats is not None:
            mat, nz = mats
        else:
            n = len(pods)
            width = self.requested.shape[1]
            mat = np.zeros((n, width), np.int64)
            nz = np.zeros((n, 2), np.int64)
            for i, pod in enumerate(pods):
                for s, v in _req_slot_pairs(self.vocab, pod):
                    if s >= width:
                        raise KeySlotOverflow()
                    mat[i, s] = v
                c, m = pod_non_zero_request(pod)
                nz[i, 0] = c
                nz[i, 1] = m
        np.add.at(self.requested, rows, mat)
        np.add.at(self.nonzero_req, rows, nz)
        np.add.at(self.pod_count, rows, 1)

    def update_usage(self, i: int, ni: NodeInfo) -> bool:
        """Refresh ONLY the pod-driven columns (requested/non-zero/pod
        count/used ports) — the single-pod delta path. Node identity
        (labels, taints, zone, avoid signatures) is untouched. Returns
        False when the caller must fall back to a full set_node (port
        table overflow changes the fallback flag)."""
        c = self.vocab.config
        used_ports = sorted(ni.used_host_ports())
        if len(used_ports) > c.node_ports or self.fallback[i]:
            return False
        self.requested[i] = 0
        for name, amount in ni.requested().items():
            if name != RESOURCE_PODS:
                s = self.vocab.slot_of_resource(name)
                if s >= self.requested.shape[1]:
                    raise KeySlotOverflow()
                self.requested[i, s] = amount
        nz_cpu, nz_mem = ni.non_zero_requested()
        self.nonzero_req[i, 0] = nz_cpu
        self.nonzero_req[i, 1] = nz_mem
        self.pod_count[i] = len(ni.pods)
        self.port_proto[i] = 0
        self.port_ip[i] = 0
        self.port_num[i] = 0
        for p_idx, (proto, ip, port) in enumerate(used_ports):
            self.port_proto[i, p_idx] = self.vocab.id(proto)
            self.port_ip[i, p_idx] = self.vocab.id(ip)
            self.port_num[i, p_idx] = port
        return True

    def arrays(self) -> Dict[str, np.ndarray]:
        out = {
            "valid": self.valid,
            "fallback": self.fallback,
            "name_id": self.name_id,
            "alloc": self.alloc,
            "requested": self.requested,
            "nonzero_req": self.nonzero_req,
            "allowed_pods": self.allowed_pods,
            "pod_count": self.pod_count,
            "label_vals": self.label_vals,
            "label_num": self.label_num,
            "label_num_ok": self.label_num_ok,
            "taint_key": self.taint_key,
            "taint_val": self.taint_val,
            "taint_effect": self.taint_effect,
            "unschedulable": self.unschedulable,
            "port_proto": self.port_proto,
            "port_ip": self.port_ip,
            "port_num": self.port_num,
            "label_dense": self.label_dense,
            "zone_id": self.zone_id,
            "zone_dense": self.zone_dense,
            "avoid_kind": self.avoid_kind,
            "avoid_uid": self.avoid_uid,
        }
        if self.image_scaled is not None:
            out["image_scaled"] = self.image_scaled
        return out


class KeySlotOverflow(Exception):
    """Raised when a label key or resource name lands beyond the current
    bank's array width — the caller rebuilds banks at the grown capacity
    (Vocab already bumped config). Also used for resource-slot overflow."""


def _zone_key(labels: Dict[str, str]) -> str:
    region = labels.get(LABEL_ZONE_REGION, "")
    zone = labels.get(LABEL_ZONE_FAILURE_DOMAIN, "")
    if not region and not zone:
        return ""
    return region + ":\x00:" + zone


def _avoid_signatures(node: Node) -> List[Tuple[int, str]]:
    """Parse the preferAvoidPods annotation into (kind_code, uid) pairs;
    malformed JSON → empty (GetAvoidPodsFromNodeAnnotations error path)."""
    import json

    ann = node.annotations.get(PREFER_AVOID_PODS_ANNOTATION, "")
    if not ann:
        return []
    try:
        avoids = json.loads(ann)
    except ValueError:
        return []
    if not isinstance(avoids, dict):
        return []
    entries = avoids.get("preferAvoidPods")
    if not isinstance(entries, list):
        return []
    out = []
    for avoid in entries:
        if not isinstance(avoid, dict):
            continue
        sig = avoid.get("podSignature")
        ref = (sig.get("podController") if isinstance(sig, dict) else None) or {}
        kind = {"ReplicationController": 1, "ReplicaSet": 2}.get(ref.get("kind"), 0)
        if kind and ref.get("uid"):
            out.append((kind, str(ref.get("uid"))))
    return out


class ImageTable:
    """Dense image-id → spread-scaled size table (image_locality.go
    scaledImageScore): scaled = int(size * numNodesWithImage / totalNodes),
    precomputed host-side per image so the kernel is a pure gather."""

    def __init__(self, vocab: Vocab):
        self.vocab = vocab

    def apply(
        self, bank: NodeBank, snapshot: Snapshot, row_of: Optional[Dict[str, int]] = None
    ) -> None:
        """row_of maps node name → bank row; defaults to snapshot enumeration
        order (the encode_snapshot layout)."""
        v = self.vocab
        if row_of is None:
            row_of = {ni.node.name: i for i, ni in enumerate(snapshot.node_infos.values())}
        node_counts = snapshot.total_image_nodes()
        total_nodes = len(snapshot.node_infos)
        # image vocabulary = every image name seen on any node
        max_id = 0
        for ni in snapshot.node_infos.values():
            for name in ni.image_sizes():
                max_id = max(max_id, v.id(name))
        # bucketed width → stable kernel shapes across snapshots
        table = np.zeros((bank.capacity, _bucket(max_id + 1, 64)), np.int64)
        for ni in snapshot.node_infos.values():
            idx = row_of.get(ni.node.name)
            if idx is None:
                continue
            for name, size in ni.image_sizes().items():
                spread = node_counts.get(name, 0) / total_nodes if total_nodes else 0.0
                table[idx, v.id(name)] = int(size * spread)
        bank.image_scaled = table


# ---------------------------------------------------------------------------
# Pod batch
# ---------------------------------------------------------------------------

@dataclass
class PodBatch:
    """Padded encoding of a batch of PENDING pods, capacity B."""

    vocab: Vocab
    capacity: int

    valid: np.ndarray = None  # [B]
    fallback: np.ndarray = None  # [B] structures overflowed; use oracle path
    label_vals: np.ndarray = None  # [B, K] int32 (pod labels, for symmetric matching)
    req: np.ndarray = None  # [B, R] int64 (GetResourceRequest: incl. init max)
    req_any: np.ndarray = None  # [B] bool: pod requests anything at all
    scoring_req: np.ndarray = None  # [B, 2] int64 (calculatePodResourceRequest)
    limit_req: np.ndarray = None  # [B, 2] int64 (getResourceLimits: cpu milli, mem bytes)
    priority: np.ndarray = None  # [B] int32
    node_name_id: np.ndarray = None  # [B] int32 spec.nodeName pin (0 = none)
    ns_id: np.ndarray = None  # [B] int32
    tol_key: np.ndarray = None  # [B, TL] int32 (0 = match-all-keys)
    tol_op: np.ndarray = None  # [B, TL] int32 TOL_*
    tol_val: np.ndarray = None  # [B, TL] int32
    tol_effect: np.ndarray = None  # [B, TL] int32 (0 = match-all-effects)
    tol_valid: np.ndarray = None  # [B, TL] bool
    nsel_key: np.ndarray = None  # [B, NS_pairs…] — nodeSelector map pairs
    # compiled required terms (nodeSelector map folded in as term-0 prefix is
    # NOT possible since map is ANDed with ORed terms; kept separate):
    sel_pair_slot: np.ndarray = None  # [B, NSP] int32 key slot (-1 pad)
    sel_pair_val: np.ndarray = None  # [B, NSP] int32
    has_required: np.ndarray = None  # [B] bool: affinity.required != nil
    term_valid: np.ndarray = None  # [B, TERMS] bool
    term_req_op: np.ndarray = None  # [B, TERMS, REQS] int32 OP_*
    term_req_slot: np.ndarray = None  # [B, TERMS, REQS] int32 (-1 = unknown key)
    term_req_vals: np.ndarray = None  # [B, TERMS, REQS, VALS] int32 (-1 pad)
    term_req_num: np.ndarray = None  # [B, TERMS, REQS] int64 Gt/Lt operand
    # preferred node-affinity terms for scoring
    pref_valid: np.ndarray = None  # [B, PT] bool
    pref_weight: np.ndarray = None  # [B, PT] int32
    pref_req_op: np.ndarray = None  # [B, PT, REQS] int32
    pref_req_slot: np.ndarray = None  # [B, PT, REQS] int32
    pref_req_vals: np.ndarray = None  # [B, PT, REQS, VALS] int32
    pref_req_num: np.ndarray = None  # [B, PT, REQS] int64
    # host ports
    port_proto: np.ndarray = None  # [B, PP] int32
    port_ip: np.ndarray = None  # [B, PP] int32
    port_num: np.ndarray = None  # [B, PP] int32 (0 pad)
    # tolerations restricted to PreferNoSchedule scoring set are derivable on
    # device (effect in {0, PREFER}) — no extra arrays needed.
    # images
    image_ids: np.ndarray = None  # [B, CI] int32 (0 pad)
    # preferAvoidPods controller signature
    ctrl_kind: np.ndarray = None  # [B] int32 (0 none, 1 RC, 2 RS)
    ctrl_uid: np.ndarray = None  # [B] int32

    def __post_init__(self):
        c = self.vocab.config
        self.key_capacity = c.key_slots
        b = self.capacity
        self.valid = np.zeros(b, bool)
        self.fallback = np.zeros(b, bool)
        self.label_vals = np.zeros((b, c.key_slots), np.int32)
        self.req = np.zeros((b, c.resource_slots), np.int64)
        self.req_any = np.zeros(b, bool)
        self.scoring_req = np.zeros((b, 2), np.int64)
        self.limit_req = np.zeros((b, 2), np.int64)  # getResourceLimits (cpu milli, mem bytes)
        self.priority = np.zeros(b, np.int32)
        self.node_name_id = np.zeros(b, np.int32)
        self.ns_id = np.zeros(b, np.int32)
        self.tol_key = np.zeros((b, c.pod_tolerations), np.int32)
        self.tol_op = np.zeros((b, c.pod_tolerations), np.int32)
        self.tol_val = np.zeros((b, c.pod_tolerations), np.int32)
        self.tol_effect = np.zeros((b, c.pod_tolerations), np.int32)
        self.tol_valid = np.zeros((b, c.pod_tolerations), bool)
        nsp = c.nsel_reqs  # nodeSelector map pair capacity
        self.sel_pair_slot = np.full((b, nsp), -1, np.int32)
        self.sel_pair_val = np.zeros((b, nsp), np.int32)
        self.has_required = np.zeros(b, bool)
        self.term_valid = np.zeros((b, c.nsel_terms), bool)
        self.term_req_op = np.zeros((b, c.nsel_terms, c.nsel_reqs), np.int32)
        self.term_req_slot = np.full((b, c.nsel_terms, c.nsel_reqs), -1, np.int32)
        self.term_req_vals = np.full((b, c.nsel_terms, c.nsel_reqs, c.nsel_vals), -1, np.int32)
        self.term_req_num = np.zeros((b, c.nsel_terms, c.nsel_reqs), np.int64)
        self.pref_valid = np.zeros((b, c.pref_terms), bool)
        self.pref_weight = np.zeros((b, c.pref_terms), np.int32)
        self.pref_req_op = np.zeros((b, c.pref_terms, c.nsel_reqs), np.int32)
        self.pref_req_slot = np.full((b, c.pref_terms, c.nsel_reqs), -1, np.int32)
        self.pref_req_vals = np.full((b, c.pref_terms, c.nsel_reqs, c.nsel_vals), -1, np.int32)
        self.pref_req_num = np.zeros((b, c.pref_terms, c.nsel_reqs), np.int64)
        self.port_proto = np.zeros((b, c.pod_ports), np.int32)
        self.port_ip = np.zeros((b, c.pod_ports), np.int32)
        self.port_num = np.zeros((b, c.pod_ports), np.int32)
        self.image_ids = np.zeros((b, c.pod_images), np.int32)
        self.ctrl_kind = np.zeros(b, np.int32)
        self.ctrl_uid = np.zeros(b, np.int32)

    # -- encoding ------------------------------------------------------------

    def _encode_requirement(self, req: NodeSelectorRequirement, is_field: bool):
        """Compile one requirement → (op, slot, vals, num) tuple."""
        v = self.vocab
        c = v.config
        op_map = {
            "In": OP_IN,
            "NotIn": OP_NOT_IN,
            "Exists": OP_EXISTS,
            "DoesNotExist": OP_DOES_NOT_EXIST,
            "Gt": OP_GT,
            "Lt": OP_LT,
        }
        vals = [-1] * c.nsel_vals
        num = 0
        if is_field:
            # only metadata.name In/NotIn with exactly 1 value is convertible
            # (NodeSelectorRequirementsAsFieldSelector); anything else makes
            # the term match nothing.
            if req.key != "metadata.name" or req.operator not in ("In", "NotIn") or len(req.values) != 1:
                return OP_NEVER, -1, vals, num, False
            op = OP_NAME_IN if req.operator == "In" else OP_NAME_NOT_IN
            vals[0] = v.id(req.values[0])
            return op, -1, vals, num, False
        op = op_map.get(req.operator)
        if op is None:
            return OP_NEVER, -1, vals, num, False
        slot = v.slot_of_key(req.key)
        overflow = False
        if op in (OP_IN, OP_NOT_IN):
            if len(req.values) > c.nsel_vals:
                overflow = True
            for j, s in enumerate(req.values[: c.nsel_vals]):
                vals[j] = v.id(s)
        elif op in (OP_GT, OP_LT):
            if len(req.values) != 1:
                return OP_NEVER, slot, vals, num, False
            n, ok = _parse_int_label(req.values[0])
            if not ok:
                return OP_NEVER, slot, vals, num, False
            num = n
        return op, slot, vals, num, overflow

    def set_pod(self, b: int, pod: Pod) -> None:
        v = self.vocab
        c = v.config
        overflow = False
        self.valid[b] = True
        self.label_vals[b] = ABSENT
        for k, val in pod.labels.items():
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.label_vals[b, s] = v.id(val)
        # resources
        self.req[b] = 0
        any_req = False
        for name, amount in pod.resource_request().items():
            if name == RESOURCE_PODS:
                continue
            if amount != 0:
                any_req = True
            s = v.slot_of_resource(name)
            if s >= self.req.shape[1]:
                raise KeySlotOverflow()
            self.req[b, s] = amount
        self.req_any[b] = any_req
        s_cpu, s_mem = _pod_scoring_request(pod)
        self.scoring_req[b, 0] = s_cpu
        self.scoring_req[b, 1] = s_mem
        l_cpu, l_mem = _pod_resource_limits(pod)
        self.limit_req[b, 0] = l_cpu
        self.limit_req[b, 1] = l_mem
        self.priority[b] = pod.get_priority()
        self.node_name_id[b] = v.id(pod.node_name) if pod.node_name else 0
        self.ns_id[b] = v.id(pod.namespace)
        # tolerations
        self.tol_valid[b] = False
        if len(pod.tolerations) > c.pod_tolerations:
            overflow = True
        for t_idx, tol in enumerate(pod.tolerations[: c.pod_tolerations]):
            self.tol_key[b, t_idx] = v.id(tol.key) if tol.key else 0
            self.tol_op[b, t_idx] = TOL_EXISTS if tol.operator == "Exists" else TOL_EQUAL
            self.tol_val[b, t_idx] = v.id(tol.value) if tol.value else v.id("")
            self.tol_effect[b, t_idx] = _EFFECT_CODE.get(tol.effect, 0) if tol.effect else 0
            self.tol_valid[b, t_idx] = True
        # nodeSelector map (ANDed pairs)
        self.sel_pair_slot[b] = -1
        pairs = list(pod.node_selector.items())
        if len(pairs) > self.sel_pair_slot.shape[1]:
            overflow = True
        for j, (k, val) in enumerate(pairs[: self.sel_pair_slot.shape[1]]):
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            self.sel_pair_slot[b, j] = s
            self.sel_pair_val[b, j] = v.id(val)
        # required node affinity
        self.has_required[b] = False
        self.term_valid[b] = False
        self.term_req_op[b] = OP_PAD
        aff = pod.affinity
        na = aff.node_affinity if aff is not None else None
        if na is not None and na.required is not None:
            self.has_required[b] = True
            terms = na.required.node_selector_terms
            if len(terms) > c.nsel_terms:
                overflow = True
            for t_idx, term in enumerate(terms[: c.nsel_terms]):
                reqs = [(r, False) for r in term.match_expressions] + [
                    (r, True) for r in term.match_fields
                ]
                if not reqs:
                    continue  # empty term matches nothing → leave invalid
                self.term_valid[b, t_idx] = True
                if len(reqs) > c.nsel_reqs:
                    overflow = True
                for r_idx, (r, is_field) in enumerate(reqs[: c.nsel_reqs]):
                    op, slot, vals, num, ovf = self._encode_requirement(r, is_field)
                    overflow = overflow or ovf
                    if slot >= self.key_capacity:
                        raise KeySlotOverflow()
                    self.term_req_op[b, t_idx, r_idx] = op
                    self.term_req_slot[b, t_idx, r_idx] = slot
                    self.term_req_vals[b, t_idx, r_idx] = vals
                    self.term_req_num[b, t_idx, r_idx] = num
        # preferred node affinity
        self.pref_valid[b] = False
        self.pref_req_op[b] = OP_PAD
        if na is not None and na.preferred:
            prefs = na.preferred
            if len(prefs) > c.pref_terms:
                overflow = True
            for t_idx, pref in enumerate(prefs[: c.pref_terms]):
                if pref.weight == 0:
                    continue
                self.pref_valid[b, t_idx] = True
                self.pref_weight[b, t_idx] = pref.weight
                reqs = pref.preference.match_expressions
                if len(reqs) > c.nsel_reqs:
                    overflow = True
                for r_idx, r in enumerate(reqs[: c.nsel_reqs]):
                    op, slot, vals, num, ovf = self._encode_requirement(r, False)
                    overflow = overflow or ovf
                    if slot >= self.key_capacity:
                        raise KeySlotOverflow()
                    self.pref_req_op[b, t_idx, r_idx] = op
                    self.pref_req_slot[b, t_idx, r_idx] = slot
                    self.pref_req_vals[b, t_idx, r_idx] = vals
                    self.pref_req_num[b, t_idx, r_idx] = num
        # host ports
        self.port_num[b] = 0
        ports = pod.host_ports()
        if len(ports) > c.pod_ports:
            overflow = True
        for p_idx, (proto, ip, port) in enumerate(ports[: c.pod_ports]):
            self.port_proto[b, p_idx] = v.id(proto)
            self.port_ip[b, p_idx] = v.id(ip)
            self.port_num[b, p_idx] = port
        # images
        self.image_ids[b] = 0
        if len(pod.containers) > c.pod_images:
            overflow = True
        for i_idx, cont in enumerate(pod.containers[: c.pod_images]):
            if cont.image:
                self.image_ids[b, i_idx] = v.strings.lookup(normalized_image_name(cont.image))
        # controller signature
        self.ctrl_kind[b] = 0
        self.ctrl_uid[b] = 0
        for ref in pod.owner_references:
            if ref.get("controller"):
                kind = {"ReplicationController": 1, "ReplicaSet": 2}.get(ref.get("kind"), 0)
                if kind:
                    self.ctrl_kind[b] = kind
                    self.ctrl_uid[b] = v.id(str(ref.get("uid", "")))
                break
        self.fallback[b] = overflow

    def arrays(self) -> Dict[str, np.ndarray]:
        return {
            "valid": self.valid,
            "fallback": self.fallback,
            "label_vals": self.label_vals,
            "req": self.req,
            "req_any": self.req_any,
            "scoring_req": self.scoring_req,
            "limit_req": self.limit_req,
            "priority": self.priority,
            "node_name_id": self.node_name_id,
            "ns_id": self.ns_id,
            "tol_key": self.tol_key,
            "tol_op": self.tol_op,
            "tol_val": self.tol_val,
            "tol_effect": self.tol_effect,
            "tol_valid": self.tol_valid,
            "sel_pair_slot": self.sel_pair_slot,
            "sel_pair_val": self.sel_pair_val,
            "has_required": self.has_required,
            "term_valid": self.term_valid,
            "term_req_op": self.term_req_op,
            "term_req_slot": self.term_req_slot,
            "term_req_vals": self.term_req_vals,
            "term_req_num": self.term_req_num,
            "pref_valid": self.pref_valid,
            "pref_weight": self.pref_weight,
            "pref_req_op": self.pref_req_op,
            "pref_req_slot": self.pref_req_slot,
            "pref_req_vals": self.pref_req_vals,
            "pref_req_num": self.pref_req_num,
            "port_proto": self.port_proto,
            "port_ip": self.port_ip,
            "port_num": self.port_num,
            "image_ids": self.image_ids,
            "ctrl_kind": self.ctrl_kind,
            "ctrl_uid": self.ctrl_uid,
        }


# bucket policy lives in the compile subsystem's shape ladder (ONE
# quantizer shared by encoders, driver, and the AOT warmup service — they
# must never disagree about which shapes exist); these names stay as the
# encoding layer's aliases
from ..compile.ladder import node_axis_bucket as _node_bucket  # noqa: E402
from ..compile.ladder import pow2_bucket as _bucket  # noqa: E402


class SigOverflow(KeySlotOverflow):
    """Signature bank out of slots — rebuild at the next bucket size."""


@dataclass
class SigBank:
    """Existing pods collapsed to LABEL SIGNATURES with per-node counts.

    Every device consumer of existing pods (the topology kernels:
    EvenPodsSpread, InterPodAffinity, SelectorSpread) matches terms against a
    pod's (namespace, labels, deleting) and then counts matches per node —
    the pod's identity never matters. Distinct (ns, labels, deleting)
    combinations number in the hundreds even in 100k-pod clusters, so
    matching runs against S signature rows instead of M pod rows and the
    per-node counts become ONE [TT, S] × [S, N] MXU matmul — this removed an
    ~11 s/batch gather+scatter wall over a 131k-row pod bank at the 10k-node
    benchmark config.

    Arrays (device dict):
      valid [S], ns_id [S], label_vals [S, K], deleting [S] — signature
      metadata, patched by dirty SIGNATURE rows;
      counts [N_cap, S] int16 — pods per (node, signature), node-major so
      the mirror patches it with dirty NODE rows.
    """

    vocab: Vocab
    capacity: int  # S
    node_capacity: int  # N rows of the counts matrix

    valid: np.ndarray = None  # [S]
    ns_id: np.ndarray = None  # [S] int32
    label_vals: np.ndarray = None  # [S, K] int32
    deleting: np.ndarray = None  # [S] bool
    counts: np.ndarray = None  # [N, S] int16

    def __post_init__(self):
        c = self.vocab.config
        self.key_capacity = c.key_slots
        s = self.capacity
        self.valid = np.zeros(s, bool)
        self.ns_id = np.zeros(s, np.int32)
        self.label_vals = np.zeros((s, c.key_slots), np.int32)
        self.deleting = np.zeros(s, bool)
        self.counts = np.zeros((self.node_capacity, s), np.int16)
        # slab bookkeeping is DRIVER-THREAD-CONFINED by the mirror's
        # contract (sync/fold planning/commit bulk-apply all run on the
        # driver thread; the commit worker never interns) — declared
        # confined so any access from a method not carrying the
        # confined(driver) mark shows up as a KTPU003 violation instead
        # of a silent refcount race
        self._sig_of: Dict[bytes, int] = {}  # ktpu: confined(driver)
        self._key_of_row: Dict[int, bytes] = {}  # ktpu: confined(driver)
        self._encode_cache: Dict[tuple, Tuple[bytes, np.ndarray, int, bool]] = {}  # ktpu: confined(driver)
        self._refs = np.zeros(s, np.int64)  # ktpu: confined(driver)
        self._free = list(range(s - 1, -1, -1))  # ktpu: confined(driver)
        self.dirty_sig_rows: Set[int] = set()  # ktpu: confined(driver)

    # ktpu: confined(driver) driver-thread slab path (mirror contract)
    def _encode_key(self, pod: Pod) -> Tuple[bytes, np.ndarray, int, bool]:
        # memoized by label CONTENT: replicas share label sets, so a
        # 4096-pod batch needs ~#specs encodes instead of one numpy row
        # build per pod. Safety rests on Vocab ids/slots being GROW-ONLY
        # and process-stable (rebuilds reuse the vocab), so cached ids can
        # never go stale; the cache dies with this bank. Bounded against
        # label-churn pathologies (the win is ~#distinct specs, so a small
        # bound keeps the hit rate while capping worst-case memory at high
        # key_slots counts).
        # per-object memo first (labels/ns/deleting are object-stable;
        # tagged by vocab + slot width so bank rebuilds reuse it but a
        # grown key space or a different test vocab invalidates it): the
        # content-tuple build below is itself ~1us/pod on the sync path
        obj_memo = pod.__dict__.get("_sig_enc_memo")
        if (
            obj_memo is not None
            and obj_memo[0] is self.vocab
            and obj_memo[1] == self.key_capacity
        ):
            return obj_memo[2]
        lk = (tuple(sorted(pod.labels.items())), pod.namespace,
              pod.deletion_timestamp is not None)
        hit = self._encode_cache.get(lk)
        if hit is not None:
            pod.__dict__["_sig_enc_memo"] = (self.vocab, self.key_capacity, hit)
            return hit
        v = self.vocab
        row = np.zeros(self.key_capacity, np.int32)
        row[:] = ABSENT
        for k, val in pod.labels.items():
            s = v.slot_of_key(k)
            if s >= self.key_capacity:
                raise KeySlotOverflow()
            row[s] = v.id(val)
        ns = v.id(pod.namespace)
        deleting = pod.deletion_timestamp is not None
        key = row.tobytes() + ns.to_bytes(4, "little") + bytes([deleting])
        if len(self._encode_cache) > 8192:
            self._encode_cache.clear()
        out = (key, row, ns, deleting)
        self._encode_cache[lk] = out
        pod.__dict__["_sig_enc_memo"] = (self.vocab, self.key_capacity, out)
        return out

    # ktpu: confined(driver) driver-thread slab path (mirror contract)
    def _intern(self, pod: Pod) -> int:
        key, row, ns, deleting = self._encode_key(pod)
        sig = self._sig_of.get(key)
        if sig is None:
            if not self._free:
                raise SigOverflow()
            sig = self._free.pop()
            self._sig_of[key] = sig
            self.valid[sig] = True
            self.ns_id[sig] = ns
            self.label_vals[sig] = row
            self.deleting[sig] = deleting
            self._key_of_row[sig] = key
            self.dirty_sig_rows.add(sig)
        return sig

    # ktpu: confined(driver) commit-fold planning runs on the driver thread
    def prepare_row(self, pod: Pod) -> int:
        """Intern a pod's signature WITHOUT taking a reference — the
        device-fold planner (commit/fold.py) needs the row index at commit
        time, BEFORE the commit deltas reach the mirror's sync(). The later
        apply_delta/apply_adds_bulk intern of the same pod is a guaranteed
        hit on this row (content-keyed, grow-only vocab), and a freshly
        allocated row with zero refs is never freed by _unref (no holder
        can release it), so pre-interning is safe. New rows land in
        dirty_sig_rows so their metadata ships via the normal dirty-row
        patch while the COUNTS arrive by device fold. Raises
        SigOverflow/KeySlotOverflow exactly like _intern (the caller skips
        the fold and falls back to the host scatter path)."""
        return self._intern(pod)

    # ktpu: confined(driver) driver-thread slab path (mirror contract)
    def _unref(self, sig: int, n: int) -> None:
        self._refs[sig] -= n
        if self._refs[sig] <= 0:
            self._refs[sig] = 0
            self.valid[sig] = False
            key = self._key_of_row.pop(sig, None)
            if key is not None:
                self._sig_of.pop(key, None)
            self._free.append(sig)
            self.dirty_sig_rows.add(sig)

    # ktpu: confined(driver) called from mirror sync/_release_node_pods
    def release_node(self, node_row: int, held: Dict[int, int]) -> None:
        """Undo a node's contribution: `held` is its {sig: count} map."""
        for sig, n in held.items():
            self.counts[node_row, sig] -= n
            self._unref(sig, n)

    # ktpu: confined(driver) mirror sync's delta walk
    def apply_delta(self, node_row: int, pod, sign: int, held: Dict[int, int]) -> None:
        """O(1) single-pod count change (the mirror's pod-delta path).
        `held` is the node's {sig: count} bookkeeping map. Raises
        KeySlotOverflow/SigOverflow like encode_node (caller rebuilds);
        a remove for an unknown signature means the books are inconsistent
        — also escalated to a rebuild."""
        if sign > 0:
            sig = self._intern(pod)
            held[sig] = held.get(sig, 0) + 1
            self._refs[sig] += 1
            self.counts[node_row, sig] += 1
            return
        key, _, _, _ = self._encode_key(pod)
        sig = self._sig_of.get(key)
        if sig is None or held.get(sig, 0) <= 0:
            raise SigOverflow()  # inconsistent books: full rebuild heals
        held[sig] -= 1
        if held[sig] == 0:
            del held[sig]
        self.counts[node_row, sig] -= 1
        self._unref(sig, 1)

    # ktpu: confined(driver) mirror sync's bulk flush
    def apply_adds_bulk(self, rows: np.ndarray, pods: Sequence, held_maps: Sequence[Dict[int, int]]) -> None:
        """apply_delta(sign=+1) over a whole commit batch: interning stays
        per pod (memoized — ~#specs real encodes), but the count and ref
        scatters collapse to two np.add.at calls. A mid-loop overflow
        leaves held/counts inconsistent; callers treat any raise as a
        rebuild signal (they do already — the mirror's sync contract)."""
        sigs = np.empty(len(pods), np.int64)
        for i, pod in enumerate(pods):
            sig = self._intern(pod)
            sigs[i] = sig
            h = held_maps[i]
            h[sig] = h.get(sig, 0) + 1
        np.add.at(self._refs, sigs, 1)
        np.add.at(self.counts, (rows, sigs), 1)

    # ktpu: confined(driver) mirror sync/rebuild re-count
    def encode_node(self, node_row: int, pods) -> Dict[int, int]:
        """Count a node's pods into signatures → the {sig: count} map the
        caller must keep for the matching release_node. Raises
        KeySlotOverflow/SigOverflow for the mirror's rebuild-bigger loop
        (partial refs are rolled back first so a rebuild isn't required for
        consistency — but the caller always rebuilds anyway)."""
        held: Dict[int, int] = {}
        try:
            for pod in pods:
                sig = self._intern(pod)
                held[sig] = held.get(sig, 0) + 1
                self._refs[sig] += 1
                self.counts[node_row, sig] += 1
        except KeySlotOverflow:
            self.release_node(node_row, held)
            raise
        return held

    def arrays(self) -> Dict[str, np.ndarray]:
        return {
            "valid": self.valid,
            "ns_id": self.ns_id,
            "label_vals": self.label_vals,
            "deleting": self.deleting,
            "counts": self.counts,
        }


def encode_snapshot(
    snapshot: Snapshot, vocab: Optional[Vocab] = None, with_images: bool = True
) -> Tuple[NodeBank, SigBank, Dict[str, int]]:
    """Full (re-)encode of a Snapshot → (NodeBank, SigBank,
    node_row_index). The incremental path reuses the banks and calls
    set_node/encode_node for dirty rows only."""
    vocab = vocab or Vocab()
    min_sigs = 16
    while True:
        try:
            infos = list(snapshot.node_infos.values())
            bank = NodeBank(vocab, _node_bucket(len(infos)))
            row_of = {}
            for i, ni in enumerate(infos):
                bank.set_node(i, ni)
                row_of[ni.node.name] = i
            sigs = SigBank(vocab, _bucket(min_sigs), bank.capacity)
            for i, ni in enumerate(infos):
                sigs.encode_node(i, ni.pods)
            if with_images:
                ImageTable(vocab).apply(bank, snapshot)
            return bank, sigs, row_of
        except SigOverflow:
            min_sigs *= 2
        except KeySlotOverflow:
            continue  # vocab.config.key_slots already grown; rebuild
