"""HTTP client for the apiserver transport: FakeAPIServer's interface over
the wire, so Informer (and anything else written against the in-process
store) consumes a REMOTE apiserver unchanged — the client-go RESTClient +
watch.Interface analogue (tools/cache/reflector.go list+watch protocol).

RemoteAPIServer(base_url) implements list/watch/create/update/delete/get/
bind; watch() returns a Watcher-compatible object fed by a daemon thread
reading the chunked stream. GoneError maps from HTTP 410 (the informer's
relist trigger), ConflictError from 409.
"""

from __future__ import annotations

import http.client
import json
import queue
import threading
from typing import Any, List, Optional, Tuple
from urllib.parse import urlparse

from ..api.types import (
    cronjob_from_k8s,
    cronjob_to_k8s,
    daemonset_from_k8s,
    daemonset_to_k8s,
    deployment_from_k8s,
    deployment_to_k8s,
    endpoints_from_k8s,
    endpoints_to_k8s,
    hpa_from_k8s,
    hpa_to_k8s,
    namespace_from_k8s,
    namespace_to_k8s,
    job_from_k8s,
    job_to_k8s,
    limitrange_from_k8s,
    limitrange_to_k8s,
    node_from_k8s,
    node_to_k8s,
    nodemetrics_from_k8s,
    nodemetrics_to_k8s,
    pdb_from_k8s,
    pdb_to_k8s,
    pod_from_k8s,
    pod_to_k8s,
    podmetrics_from_k8s,
    podmetrics_to_k8s,
    priorityclass_from_k8s,
    priorityclass_to_k8s,
    replicaset_from_k8s,
    replicaset_to_k8s,
    replicationcontroller_from_k8s,
    replicationcontroller_to_k8s,
    resourcequota_from_k8s,
    resourcequota_to_k8s,
    service_from_k8s,
    service_to_k8s,
    serviceaccount_from_k8s,
    serviceaccount_to_k8s,
    statefulset_from_k8s,
    statefulset_to_k8s,
    clusterrole_from_k8s,
    clusterrole_to_k8s,
    clusterrolebinding_from_k8s,
    clusterrolebinding_to_k8s,
    role_from_k8s,
    role_to_k8s,
    rolebinding_from_k8s,
    rolebinding_to_k8s,
)
from ..analysis.lockorder import register_thread_role
from ..apiserver.admission import AdmissionError
from ..apiserver.auth import ForbiddenError, UnauthorizedError
from ..apiserver.http import _lease_from_k8s, _lease_to_k8s
from ..utils.events import event_from_k8s, event_to_k8s
from ..apiserver.store import ConflictError, GoneError, NotFoundError, WatchEvent, _key_of

_CODECS = {
    "pods": (pod_to_k8s, pod_from_k8s),
    "nodes": (node_to_k8s, node_from_k8s),
    "replicasets": (replicaset_to_k8s, replicaset_from_k8s),
    "deployments": (deployment_to_k8s, deployment_from_k8s),
    "jobs": (job_to_k8s, job_from_k8s),
    "events": (event_to_k8s, event_from_k8s),
    "leases": (_lease_to_k8s, _lease_from_k8s),
    "priorityclasses": (priorityclass_to_k8s, priorityclass_from_k8s),
    "statefulsets": (statefulset_to_k8s, statefulset_from_k8s),
    "daemonsets": (daemonset_to_k8s, daemonset_from_k8s),
    "services": (service_to_k8s, service_from_k8s),
    "endpoints": (endpoints_to_k8s, endpoints_from_k8s),
    "namespaces": (namespace_to_k8s, namespace_from_k8s),
    "replicationcontrollers": (replicationcontroller_to_k8s, replicationcontroller_from_k8s),
    "cronjobs": (cronjob_to_k8s, cronjob_from_k8s),
    "poddisruptionbudgets": (pdb_to_k8s, pdb_from_k8s),
    "serviceaccounts": (serviceaccount_to_k8s, serviceaccount_from_k8s),
    "resourcequotas": (resourcequota_to_k8s, resourcequota_from_k8s),
    "limitranges": (limitrange_to_k8s, limitrange_from_k8s),
    "horizontalpodautoscalers": (hpa_to_k8s, hpa_from_k8s),
    "podmetrics": (podmetrics_to_k8s, podmetrics_from_k8s),
    "nodemetrics": (nodemetrics_to_k8s, nodemetrics_from_k8s),
    "roles": (role_to_k8s, role_from_k8s),
    "clusterroles": (clusterrole_to_k8s, clusterrole_from_k8s),
    "rolebindings": (rolebinding_to_k8s, rolebinding_from_k8s),
    "clusterrolebindings": (clusterrolebinding_to_k8s, clusterrolebinding_from_k8s),
}


class _RemoteWatcher:
    """Watcher-compatible stream over a chunked HTTP watch response."""

    def __init__(self, conn: http.client.HTTPConnection, resp, from_k8s):
        self._conn = conn
        self._resp = resp
        self._from = from_k8s
        self._q: "queue.Queue[Optional[WatchEvent]]" = queue.Queue()
        self.closed = False
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    # ktpu: thread-entry(informer) the remote watch pump feeds the same
    # informer stream the in-process reflector does — same role
    def _pump(self) -> None:
        register_thread_role("informer")
        try:
            buf = b""
            while True:
                data = self._resp.read1(65536)
                if not data:
                    break
                buf += data
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    d = json.loads(line)
                    obj = self._from(d["object"])
                    rv = int(d["object"].get("metadata", {}).get("resourceVersion", 0))
                    self._q.put(WatchEvent(d["type"], obj, rv))
        except Exception:
            pass  # connection dropped: informer treats close as relist
        finally:
            self.close()

    def next(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._q.put(None)
            try:
                self._conn.close()
            except Exception:
                pass


class RemoteAPIServer:
    """FakeAPIServer's surface, HTTP-backed. Drop-in for Informer."""

    def __init__(self, base_url: str, timeout: float = 10.0,
                 token: Optional[str] = None):
        u = urlparse(base_url)
        self._host = u.hostname
        self._port = u.port or 80
        self._timeout = timeout
        # bearer-token identity (rest.Config.BearerToken): sent on every
        # request; None = anonymous (only works against an open server)
        self._token = token

    def _conn(self, timeout: Optional[float] = None) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(
            self._host, self._port, timeout=timeout or self._timeout
        )

    def _headers(self, payload: Optional[bytes] = None) -> dict:
        h = {}
        if payload:
            h["Content-Type"] = "application/json"
        if self._token:
            h["Authorization"] = f"Bearer {self._token}"
        return h

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        conn = self._conn()
        try:
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, path, body=payload, headers=self._headers(payload))
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 410:
                raise GoneError(data.decode())
            if resp.status == 409:
                raise ConflictError(data.decode())
            if resp.status == 404:
                raise NotFoundError(path)
            if resp.status == 422:
                raise AdmissionError(data.decode())
            if resp.status == 401:
                raise UnauthorizedError(data.decode())
            if resp.status == 403:
                raise ForbiddenError(data.decode())
            if resp.status >= 400:
                raise RuntimeError(f"{method} {path}: {resp.status} {data[:200]!r}")
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # -- FakeAPIServer surface ------------------------------------------------

    @staticmethod
    def _sel_params(label_selector, field_selector) -> str:
        from urllib.parse import quote

        parts = []
        for name, sel in (("labelSelector", label_selector),
                          ("fieldSelector", field_selector)):
            if sel:
                # dict = equality pairs (the informer path); str = a raw
                # wire selector passed through verbatim — the set-based
                # grammar (`k in (a,b)`, `notin`, `k`, `!k`) the server's
                # _parse_label_selector speaks
                wire = (
                    sel if isinstance(sel, str)
                    else ",".join(f"{k}={v}" for k, v in sel.items())
                )
                parts.append(f"{name}=" + quote(wire))
        return ("&" + "&".join(parts)) if parts else ""

    def list(self, kind: str, label_selector=None, field_selector=None) -> Tuple[List[Any], int]:
        qs = self._sel_params(label_selector, field_selector)
        d = self._req("GET", f"/api/v1/{kind}?l=1{qs}")
        _, from_k8s = _CODECS[kind]
        rv = int(d.get("metadata", {}).get("resourceVersion", 0))
        return [from_k8s(o) for o in d.get("items", [])], rv

    def watch(self, kind: str, since_rv: int, label_selector=None,
              field_selector=None) -> _RemoteWatcher:
        _, from_k8s = _CODECS[kind]
        qs = self._sel_params(label_selector, field_selector)
        conn = self._conn(timeout=None)  # streams block until events arrive
        conn.request(
            "GET", f"/api/v1/{kind}?watch=1&resourceVersion={since_rv}{qs}",
            headers=self._headers(),
        )
        resp = conn.getresponse()
        if resp.status == 410:
            data = resp.read()
            conn.close()
            raise GoneError(data.decode())
        if resp.status == 401:
            data = resp.read()
            conn.close()
            raise UnauthorizedError(data.decode())
        if resp.status == 403:
            data = resp.read()
            conn.close()
            raise ForbiddenError(data.decode())
        if resp.status != 200:
            data = resp.read()
            conn.close()
            raise RuntimeError(f"watch {kind}: {resp.status} {data[:200]!r}")
        return _RemoteWatcher(conn, resp, from_k8s)

    def create(self, kind: str, obj: Any) -> Any:
        to_k8s, from_k8s = _CODECS[kind]
        return from_k8s(self._req("POST", f"/api/v1/{kind}", to_k8s(obj)))

    def update(self, kind: str, obj: Any, check_rv: bool = False) -> Any:
        to_k8s, from_k8s = _CODECS[kind]
        body = to_k8s(obj)
        if not check_rv:
            body.get("metadata", {}).pop("resourceVersion", None)
        return from_k8s(
            self._req("PUT", f"/api/v1/{kind}/{_key_of(obj)}", body)
        )

    def delete(self, kind: str, key: str) -> None:
        self._req("DELETE", f"/api/v1/{kind}/{key}")

    def get(self, kind: str, key: str) -> Any:
        _, from_k8s = _CODECS[kind]
        return from_k8s(self._req("GET", f"/api/v1/{kind}/{key}"))

    def bind(self, namespace: str, name: str, node_name: str) -> None:
        self._req(
            "POST",
            f"/api/v1/pods/{namespace}/{name}/binding",
            {"target": {"kind": "Node", "name": node_name}},
        )

    def update_pod_status(self, namespace: str, name: str, *,
                          nominated_node_name=None) -> Any:
        """PUT pods/{name}/status — the preemption nomination write,
        FakeAPIServer.update_pod_status's surface over the wire."""
        _, from_k8s = _CODECS["pods"]
        body = {"status": {}}
        if nominated_node_name is not None:
            body["status"]["nominatedNodeName"] = nominated_node_name
        return from_k8s(
            self._req("PUT", f"/api/v1/pods/{namespace}/{name}/status", body)
        )
