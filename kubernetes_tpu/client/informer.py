"""Reflector + shared informer: the list+watch replication protocol.

client-go equivalents (SURVEY §2.4 item 3):
  Reflector.ListAndWatch (tools/cache/reflector.go:184) — list, sync the
  local store, then consume the watch stream; relist from scratch on 410
  Gone (compaction) or a closed stream.
  sharedIndexInformer (shared_informer.go:125/:448) — a thread-safe local
  store of the latest objects plus handler fan-out with (old, new) pairs.

This is the scheduler's ONLY ingestion path in standalone mode: the
watch → EventHandlers → cache/queue → TensorMirror dirty-row patch chain
(SURVEY §3.3) starts here.

The pod-ingest plane (kubernetes_tpu/ingest) rides this thread by
design: `PriorityQueue.add/update` run inside the handler dispatch
below, so a pending pod's tensor row is ENCODED HERE — on the informer
thread, once per distinct spec — and the scheduling loop's dispatch
reduces to an index pop (the reference's own scaling move: the informer
does the decode/index work, scheduleOne only pops keys). Handlers
therefore stay cheap-but-not-free; the reflector's recover-and-restart
discipline below already tolerates a slow or raising handler without
killing replication for the kind.

Failure discipline (the reference reflector's backoff-manager shape): a
failing LIST retries under capped exponential backoff with jitter — the
seed's flat 0.05s-forever retry was a hot loop against a down apiserver.
Every relist (initial sync, 410 Gone, stream close, handler error, list
error) counts into `scheduler_informer_relists_total{kind}` with the
last reason kept on the informer (`last_relist_reason`). Handler
dispatch is at-least-once: the store commits AFTER the handlers ran, so
a raising handler drops the stream and the relist re-delivers the event
instead of silently losing it.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Any, Callable, Dict, List, Optional

from ..analysis.lockorder import audited_lock, register_thread_role
from ..apiserver.store import (
    ADDED,
    ConflictError,
    DELETED,
    FakeAPIServer,
    GoneError,
    MODIFIED,
    _key_of,
)
from ..metrics import metrics as M

logger = logging.getLogger("kubernetes_tpu.informer")

#: failed-list retry backoff (reflector.go backoff manager shape): capped
#: exponential with jitter, reset on the first successful list
RELIST_BACKOFF_INITIAL = 0.05
RELIST_BACKOFF_MAX = 5.0


class _RelistHandlerError(Exception):
    """A handler raised during RELIST dispatch (store not committed —
    the retry re-delivers). Distinct from a list error so the retry is
    labeled honestly."""


class Informer:
    """One resource kind's reflector loop + local store + handlers."""

    def __init__(self, api: FakeAPIServer, kind: str,
                 label_selector: Optional[Dict[str, str]] = None,
                 field_selector: Optional[Dict[str, str]] = None,
                 fault_plan=None):
        self.api = api
        self.kind = kind
        # server-side filtering (labels/fields on list+watch): a kubelet's
        # pod informer passes {"spec.nodeName": <node>} so the apiserver
        # never fans it the whole cluster's pod events
        self.label_selector = label_selector
        self.field_selector = field_selector
        self._store: Dict[str, Any] = {}
        self._lock = audited_lock("informer-store")
        self._handlers: List[Dict[str, Callable]] = []
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # relist observability: the counter is the per-kind metric
        # (scheduler_informer_relists_total); the reason/error strings
        # answer "why did replication restart" without a log dive
        self.last_relist_reason: Optional[str] = None
        self.last_relist_error: Optional[str] = None
        # fault plane (kubernetes_tpu/faults): watch-break / list-error
        # injection sites; None = one attribute read per event
        self.fault_plan = fault_plan

    # -- registration ---------------------------------------------------------

    def add_event_handler(
        self,
        on_add: Optional[Callable[[Any], None]] = None,
        on_update: Optional[Callable[[Any, Any], None]] = None,
        on_delete: Optional[Callable[[Any], None]] = None,
    ) -> None:
        self._handlers.append({"add": on_add, "update": on_update, "delete": on_delete})

    def _dispatch(self, kind: str, *args) -> None:
        for h in self._handlers:
            fn = h.get(kind)
            if fn is not None:
                fn(*args)

    # -- store views ----------------------------------------------------------

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._store.get(key)

    def list(self) -> List[Any]:
        with self._lock:
            return list(self._store.values())

    def has_synced(self) -> bool:
        return self._synced.is_set()

    def relists(self) -> int:
        """Completed relists for THIS kind, from the process-global
        counter (the metric is the source of truth; the old test-only
        `relist_count` attribute is gone)."""
        return int(M.informer_relists.value(self.kind))

    # -- the loop -------------------------------------------------------------

    def start(self) -> "Informer":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"informer-{self.kind}"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # in-process store: hurry the reflector loop out of its blocking
        # next() by dropping the server-side streams. A remote apiserver
        # has no such admin hook — the loop exits on its 0.2s poll and the
        # client-side watcher is closed in _run's finally.
        close = getattr(self.api, "close_watchers", None)
        if close is not None:
            close(self.kind)
        if self._thread:
            self._thread.join(timeout=5)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    def _backoff_wait(self, backoff: float) -> float:
        """One rung of the relist retry ladder: jittered stop-aware wait,
        then return the doubled (capped) delay — single-sourced so every
        failure path (list error, relist handler error, watch error)
        retries with identical shape."""
        self._stop.wait(backoff * random.uniform(0.8, 1.2))
        return min(backoff * 2, RELIST_BACKOFF_MAX)

    # ktpu: thread-entry(informer) the reflector loop: every handler
    # dispatch (EventHandlers → cache/queue/slabs) runs on this thread
    def _run(self) -> None:
        register_thread_role("informer")
        reason = "sync"  # first relist is the initial LIST
        backoff = RELIST_BACKOFF_INITIAL
        while not self._stop.is_set():
            try:
                rv = self._relist(reason)
            except _RelistHandlerError as e:
                # a handler raised mid-relist-dispatch: the store was NOT
                # committed (commit-after-dispatch, like _apply), so the
                # retry's diff re-delivers the interrupted events
                self.last_relist_error = repr(e.__cause__ or e)
                reason = "handler-error"
                backoff = self._backoff_wait(backoff)
                continue
            except Exception as e:
                # capped exponential backoff + jitter (the reference
                # reflector's backoff manager) — the seed retried a
                # failing list every flat 0.05s forever, a hot loop
                # against a down apiserver
                self.last_relist_error = repr(e)
                reason = "list-error"
                backoff = self._backoff_wait(backoff)
                continue
            backoff = RELIST_BACKOFF_INITIAL  # success resets the ladder
            self._synced.set()
            try:
                watcher = self.api.watch(
                    self.kind, rv,
                    label_selector=self.label_selector,
                    field_selector=self.field_selector,
                )
            except GoneError:
                reason = "gone"
                continue  # immediately relist (410: history compacted)
            except Exception as e:
                # a failing WATCH call retries through the same ladder
                self.last_relist_error = repr(e)
                reason = "watch-error"
                backoff = self._backoff_wait(backoff)
                continue
            try:
                while not self._stop.is_set():
                    ev = watcher.next(timeout=0.2)
                    if ev is None:
                        if watcher.closed:
                            reason = "stream-closed"
                            break  # stream ended → relist (reflector restart)
                        continue
                    fp = self.fault_plan
                    if fp is not None and fp.fire("watch-break", self.kind):
                        # injected mid-stream break: drop the stream and
                        # recover through the normal relist path
                        reason = "watch-break"
                        break
                    try:
                        self._apply(ev.type, ev.obj)
                    except Exception:
                        # a broken handler must not kill replication for the
                        # kind — log, drop the stream, relist (the reference
                        # Reflector's recover-and-restart discipline). The
                        # store commits AFTER dispatch (_apply), so the
                        # relist diff re-delivers this event: at-least-once,
                        # never silent loss.
                        logger.exception(
                            "informer %s: handler failed on %s; relisting",
                            self.kind, ev.type,
                        )
                        reason = "handler-error"
                        break
            finally:
                watcher.close()

    def _relist(self, reason: str) -> int:
        """The list half of ListAndWatch: replace the store, synthesizing
        add/update/delete diffs against the previous contents (DeltaFIFO
        Replace/Sync semantics)."""
        fp = self.fault_plan
        if fp is not None:  # injection site: apiserver list error
            fp.raise_if("list-error", self.kind)
        items, rv = self.api.list(
            self.kind,
            label_selector=self.label_selector,
            field_selector=self.field_selector,
        )
        fresh = {_key_of(o): o for o in items}
        with self._lock:
            old = self._store
        # dispatch BEFORE committing the store (the _apply discipline):
        # if a handler raises mid-diff, the store still holds `old`, so
        # the retry's diff re-delivers the interrupted events instead of
        # coming back empty and silently losing them
        try:
            for key, obj in fresh.items():
                prev = old.get(key)
                if prev is None:
                    self._dispatch("add", obj)
                elif prev.resource_version != obj.resource_version:
                    self._dispatch("update", prev, obj)
            for key, obj in old.items():
                if key not in fresh:
                    self._dispatch("delete", obj)
        except Exception as e:
            logger.exception(
                "informer %s: handler failed during relist dispatch; "
                "store NOT committed — retrying", self.kind,
            )
            raise _RelistHandlerError(str(e)) from e
        with self._lock:
            self._store = fresh
        self.last_relist_reason = reason
        M.informer_relists.inc(self.kind)
        return rv

    def _apply(self, type_: str, obj: Any) -> None:
        key = _key_of(obj)
        with self._lock:
            prev = self._store.get(key)
        # dispatch BEFORE committing the store: if a handler raises, the
        # stream drops and the relist diffs the fresh list against the
        # store — a store already containing this object would diff
        # empty and silently LOSE the event for every handler. Commit-
        # after-dispatch makes delivery at-least-once (the reference's
        # DeltaFIFO pop-after-process), at the cost of a possible
        # duplicate dispatch to handlers that succeeded before the raise
        # (handlers are idempotent per the queue/cache add contracts).
        if type_ == ADDED:
            if prev is None:
                self._dispatch("add", obj)
            else:  # replayed history can repeat adds — degrade to update
                self._dispatch("update", prev, obj)
        elif type_ == MODIFIED:
            if prev is None:
                self._dispatch("add", obj)
            else:
                self._dispatch("update", prev, obj)
        elif type_ == DELETED and prev is not None:
            self._dispatch("delete", obj)
        with self._lock:
            if type_ == DELETED:
                self._store.pop(key, None)
            else:
                self._store[key] = obj


def start_scheduler_informers(
    api: FakeAPIServer, handlers, fault_plan=None
) -> Dict[str, Informer]:
    """AddAllEventHandlers (eventhandlers.go:380): wire pod + node informers
    into the scheduler's EventHandlers. Returns the informers keyed by kind
    (caller stops them)."""
    pods = Informer(api, "pods", fault_plan=fault_plan)
    pods.add_event_handler(
        on_add=handlers.on_pod_add,
        on_update=handlers.on_pod_update,
        on_delete=handlers.on_pod_delete,
    )
    nodes = Informer(api, "nodes", fault_plan=fault_plan)
    nodes.add_event_handler(
        on_add=handlers.on_node_add,
        on_update=lambda old, new: handlers.on_node_update(old, new),
        on_delete=handlers.on_node_delete,
    )
    pods.start()
    nodes.start()
    return {"pods": pods, "nodes": nodes}


class BindMismatchError(ConflictError):
    """A bind 409 whose pod is bound to a DIFFERENT node than asked — a
    double-schedule, never a replay. Escalates through the bind-failure
    path (backoff + scheduler_bind_failures_total) after being counted
    loudly as outcome=mismatch."""


class APIBinder:
    """Binder that POSTs the binding subresource at the fake apiserver —
    the real bind path (factory.go:713-725): the informer's MODIFIED echo
    confirms the assumed pod in the cache.

    IDEMPOTENT under at-least-once delivery: the binding subresource
    409s for ANY already-bound pod (BindingREST semantics), so a bind
    replayed after a crash — the POST landed, the process died before
    the bookkeeping, the restarted drain re-issues it — resolves the
    Conflict by reading the pod back: bound to the SAME node means the
    first attempt won and this one counts as success (outcome=benign,
    scheduler_bind_conflicts_total); a DIFFERENT node means a real
    double-schedule and raises BindMismatchError. The commit path
    therefore never routes a benign replay to the bind-failure backoff
    tier."""

    def __init__(self, api: FakeAPIServer):
        self.api = api

    def bind(self, pod, node_name: str) -> None:
        try:
            self.api.bind(pod.namespace, pod.name, node_name)
        except ConflictError as e:
            try:
                bound = self.api.get("pods", pod.key()).node_name
            except Exception:
                bound = None
            if bound == node_name:
                M.bind_conflicts.inc("benign")
                return  # replay of a bind that already landed: success
            M.bind_conflicts.inc("mismatch")
            raise BindMismatchError(
                f"pod {pod.key()}: asked {node_name}, bound to {bound!r} "
                f"({e})"
            ) from e
