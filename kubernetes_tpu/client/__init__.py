"""client-go equivalent: reflector/informer machinery + the API binder."""

from .informer import APIBinder, Informer, start_scheduler_informers

__all__ = ["APIBinder", "Informer", "start_scheduler_informers"]
