"""client-go equivalent: reflector/informer machinery + the API binder."""

from .informer import APIBinder, Informer, start_scheduler_informers
from .remote import RemoteAPIServer

__all__ = ["APIBinder", "Informer", "RemoteAPIServer", "start_scheduler_informers"]
