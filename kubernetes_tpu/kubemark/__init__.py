"""Hollow-node runtime: the kubemark analogue.

The reference's hollow kubelet (pkg/kubemark/hollow_kubelet.go:64) runs a
real kubelet against fake container/volume managers: it watches for pods
bound to its node, "runs" them (status → Running), and heartbeats node
status — so scheduler-side binds get confirmed from the NODE side and node
health is a live signal, not a test fixture. This package is that loop
over the fake apiserver:

  * HollowKubelet — one node agent: registers (or adopts) its Node object,
    acks pods bound to it (phase Pending → Running, Ready condition),
    marks them Failed on stop if configured, and heartbeats the node Ready
    condition on an interval.
  * HollowCluster — N hollow kubelets sharing one informer set (the
    kubemark controller shape, pkg/kubemark/controller.go).

With the nodelifecycle controller's heartbeat-staleness monitor, killing a
HollowKubelet makes the whole failure path autonomous: heartbeats stop →
Ready goes Unknown → taints → NoExecute eviction → ReplicaSet refill →
scheduler re-place. No test reaches into a node's conditions by hand.
"""

from .hollow import HollowCluster, HollowKubelet

__all__ = ["HollowCluster", "HollowKubelet"]
