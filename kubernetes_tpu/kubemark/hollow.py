"""Hollow kubelet + hollow cluster (pkg/kubemark/hollow_kubelet.go).

The hollow kubelet's job in kubemark is to be a REAL node agent with fake
pod execution: the scheduler's bind lands on the apiserver, the kubelet's
pod watch picks it up, admits it instantly (fake runtime), and writes the
Running status back — closing the bind → node-ack → informer-confirm loop
the reference relies on (and round-2's verdict flagged as self-fed here).

Node health is a heartbeat on a LEASE object, not the Node: Kubernetes
moved kubelet heartbeats to coordination/v1 Leases (NodeLease) precisely
because per-heartbeat Node updates fan a MODIFIED event to every node
watcher — at 100 nodes x 2 beats/s that is ~200 scheduler queue flushes
per second of pure churn. Each kubelet renews `node-<name>` in the
"leases" kind; the nodelifecycle controller reads the lease's renew time
for staleness (monitorNodeHealth's grace-period semantics) and only
Ready-status TRANSITIONS touch the Node object.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..api.types import Node, Pod
from ..apiserver.store import ConflictError, NotFoundError


def node_lease_name(node_name: str) -> str:
    return f"node-{node_name}"


class HollowKubelet:
    """One node's agent loop over the (fake or remote) apiserver."""

    def __init__(
        self,
        api,
        node: Node,
        pod_informer=None,
        heartbeat_s: float = 1.0,
    ):
        self.api = api
        self.node_name = node.name
        self._node = node
        self._pod_informer = pod_informer
        self.heartbeat_s = heartbeat_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.acked = 0  # pods transitioned Pending → Running

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "HollowKubelet":
        self._register()
        # ktpu: thread-entry(kubelet) one heartbeat/ack agent per node
        self._thread = threading.Thread(
            target=self._run, name=f"hollow-{self.node_name}", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        """Kill the agent (a node crash: heartbeats simply stop)."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _register(self) -> None:
        """Create-or-adopt the Node object (Ready=True once) and start the
        lease (kubelet registerWithAPIServer + NodeLease semantics)."""
        try:
            existing = self.api.get("nodes", self.node_name)
        except (KeyError, NotFoundError):
            existing = None
        if existing is None:
            self._node.conditions = [
                c for c in self._node.conditions if c.get("type") != "Ready"
            ] + [{"type": "Ready", "status": "True"}]
            self.api.create("nodes", self._node)
        self._heartbeat()

    # -- the loop ------------------------------------------------------------

    def _run(self) -> None:
        next_beat = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_beat:
                try:
                    self._heartbeat()
                except Exception:
                    pass  # apiserver restart: retry next tick
                next_beat = now + self.heartbeat_s
            try:
                self._ack_pods()
            except Exception:
                pass
            self._stop.wait(0.05)

    def _heartbeat(self) -> None:
        """Renew the node lease (NodeLease heartbeat): this kubelet is the
        lease's only writer, so a plain update suffices — and the Node
        object stays untouched, keeping heartbeats off the node watch."""
        from ..utils.leaderelection import LeaderElectionRecord

        name = node_lease_name(self.node_name)
        rec = LeaderElectionRecord(
            holder_identity=self.node_name,
            lease_duration_s=self.heartbeat_s,
            renew_time=time.time(),
            name=name,
        )
        try:
            self.api.update("leases", rec)
        except (KeyError, NotFoundError):
            try:
                self.api.create("leases", rec)
            except ConflictError:
                pass  # racing first beat: next tick renews

    def _pods(self) -> List[Pod]:
        if self._pod_informer is not None:
            return self._pod_informer.list()
        # no informer wired: list ONLY this node's pods (the kubelet's
        # spec.nodeName field selector — reflector.go's pods-by-node watch)
        pods, _ = self.api.list(
            "pods", field_selector={"spec.nodeName": self.node_name}
        )
        return pods

    def _ack_pods(self) -> None:
        """Admit + 'run' every pod bound here that is still Pending
        (syncLoop with a fake runtime: admission always succeeds, start
        latency zero)."""
        for p in self._pods():
            if p.node_name != self.node_name or p.phase != "Pending":
                continue
            # never mutate the informer-cached object: a failed/raced update
            # would leave the shared cache marked Running with no server-side
            # transition, and the write would race the informer thread. The
            # clone is what we send; the cache changes only via MODIFIED.
            running = p.with_node(p.node_name)
            running.phase = "Running"
            running.conditions = [
                c for c in p.conditions if c.get("type") != "Ready"
            ] + [{"type": "Ready", "status": "True"}]
            try:
                self.api.update("pods", running)
                self.acked += 1
            except (KeyError, NotFoundError, ConflictError):
                pass  # deleted or raced: next tick reconverges


class HollowCluster:
    """N hollow kubelets. By default each kubelet runs its own
    field-selected pod informer (`spec.nodeName=<node>`) — the real
    kubelet topology: the apiserver filters server-side, so node agents
    never receive the whole cluster's pod events. `shared_informer=True`
    restores the single-watch kubemark-controller shape (cheaper for
    thousands of in-process kubelets in one test)."""

    def __init__(self, api, nodes: List[Node], heartbeat_s: float = 1.0,
                 shared_informer: bool = False):
        from ..client.informer import Informer

        self.api = api
        self.pod_informer = Informer(api, "pods") if shared_informer else None
        self._informers: List = []
        self.kubelets: Dict[str, HollowKubelet] = {}
        for n in nodes:
            if shared_informer:
                inf = self.pod_informer
            else:
                inf = Informer(
                    api, "pods", field_selector={"spec.nodeName": n.name}
                )
                self._informers.append(inf)
            self.kubelets[n.name] = HollowKubelet(
                api, n, pod_informer=inf, heartbeat_s=heartbeat_s
            )

    def start(self) -> "HollowCluster":
        if self.pod_informer is not None:
            self.pod_informer.start()
            self.pod_informer.wait_for_sync()
        for inf in self._informers:
            inf.start()
        for inf in self._informers:
            inf.wait_for_sync()
        for k in self.kubelets.values():
            k.start()
        return self

    def kill(self, node_name: str) -> None:
        """Crash one node agent (heartbeats stop; pods stay Running on the
        apiserver until the lifecycle controller evicts them)."""
        self.kubelets[node_name].stop()

    def stop(self) -> None:
        for k in self.kubelets.values():
            k.stop()
        if self.pod_informer is not None:
            self.pod_informer.stop()
        for inf in self._informers:
            inf.stop()
