"""Device-resident term bank: the TermStage slab's on-device twin.

A thin specialization of the ingest plane's slab uploader
(ingest/bank.StageBank): the slab uploads once, then only the rows fresh
entries touched cross the wire — batched, off the driver thread
("terms-upload" worker), chunked at TERM_RUNGS, every program (row
scatters AND the index-gather prologue) routed through the compile plan
as a KIND_TERM spec so term staging never compiles mid-drain. Double
buffering, the synthetic re-warm after slab growth, and the non-donated
scatter discipline are all inherited — see the StageBank docstring.

On a mesh the bank places through the mirror's `_to_dev` recipe with
node_major=False (term rows are replicated, exactly like the legacy
per-batch term upload), so warmed executables match dispatched ones.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..compile.ladder import KIND_TERM, SolveSpec
from ..ingest.bank import StageBank

#: dirty-row scatter rungs for the term slab (the STAGE_RUNGS idea; term
#: entries are a few rows each, so fresh-entry bursts are small)
TERM_RUNGS = (16, 64, 256)


class TermBankDevice(StageBank):
    """Keeps a device copy of a TermStage slab patched from its dirty
    rows. Shares the slab's RLock (role "terms") for all slab-coupled
    state, like StageBank shares the pod slab's."""

    THREAD_NAME = "terms-upload"
    PLANE = "terms"  # fault-plane breaker identity (kubernetes_tpu/faults)
    # slab uploads/scatters ledger under their own kind so the
    # per-dispatch "terms" kind (index/owner vectors vs the legacy
    # full-table upload) stays a clean A/B — the stage-vs-pods split
    LEDGER_KIND = "term_bank"
    RUNGS = TERM_RUNGS

    def _patch_spec(self, host: Dict, rb: int) -> SolveSpec:
        """The term-row scatter's XLA signature: b = row rung, s = slab
        row capacity, structure from the HOST dict being scattered (the
        StageBank contract — synthetic warms may run against capacity
        snapshots that differ from the live slab mid-rebuild)."""
        structure = ",".join(
            f"{k}{list(v.shape[1:])}" for k, v in sorted(host.items())
        )
        return SolveSpec(
            kind=KIND_TERM, b=rb, s=next(iter(host.values())).shape[0],
            config_repr="patch|" + structure,
        )

    def gather_spec(self, t: int, capacity: Optional[int] = None) -> SolveSpec:
        """The index-gather prologue's XLA signature: t = term-index
        vector rung (the driver's monotone term bucket), s = slab row
        capacity."""
        return SolveSpec(
            kind=KIND_TERM, t=t, s=capacity or self.stage.capacity,
            config_repr="gather",
        )
