"""Host-side term slab: enqueue-time compilation of a pod's topology terms.

The ingest plane (kubernetes_tpu/ingest) moved the pod-ROW encode to
admission time; this module does the same for the last host-built
per-batch structure on the covered path — the batch TermBank that
`state/terms.compile_batch_terms` rebuilt per dispatch (the inter-pod-
affinity config's measured wall, PERF round 10). A `TermStage` interns
each distinct pod spec's term set ONCE, as an ENTRY owning a small list
of rows in a `state/terms.TermBank` used as a slab, refcounted by the
queue entries that hold it. Replicas of one controller share one entry;
a dispatch then ships int32 (row, owner) index vectors and gathers the
per-batch term-table union on device (terms_plane/gather.py).

Every row is encoded through `state/terms.encode_pod_terms` — the SAME
helper `compile_batch_terms` writes from, in the same canonical per-pod
order — so concatenating entries in rep order reproduces the host-built
table bit-for-bit (the `owner` column, rewritten on device from the
shipped owner vector, is the only per-batch field).

Generation discipline (the PodStage contract): entry ids are monotone and
never reused; update/delete between enqueue and pop frees the last
holder's entry (any popped copy sees the mismatch and re-stages at
dispatch, counted); a slab rebuild (row-capacity growth, vocab key-width
growth) drops every entry. Spreading selectors (SelectorSpread's service/
RC listers) are part of the intern key, so a service change between
enqueue and dispatch is ordinary staleness, not a wrong answer.

Thread safety: one RLock (role "terms") around all bookkeeping, shared
with the device twin (bank.TermBankDevice). Lock order where both are
held: queue lock → terms lock; the slab never calls into the queue.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_rlock
from ..state.tensors import KeySlotOverflow, _bucket, spec_key
from ..state.terms import TermBank, encode_pod_terms

#: slab row capacity floor and hard ceiling (pow-2 rungs in between). One
#: entry per DISTINCT pending (spec, selectors) pair, a handful of rows
#: each — workload-bounded like the pod slab, so the ceiling is a safety
#: valve, not a sizing concern.
MIN_CAPACITY = 256
MAX_CAPACITY = 16384

_UNSET = object()


class TermEntry:
    """One interned term set: the slab rows it owns (in canonical encode
    order) plus everything the dispatch needs host-side without touching
    the row arrays — aux bits, present kinds, topology slots, overflow."""

    __slots__ = (
        "rows", "gen", "refs", "key", "self_aff_match", "has_aff",
        "has_anti", "n_sel_spread", "kinds", "topo_slots", "overflow",
    )

    def __init__(self, rows, gen, key, aux, kinds, topo_slots, overflow):
        self.rows: Tuple[int, ...] = rows
        self.gen = gen
        self.refs = 0
        self.key = key
        self.self_aff_match = aux["self_aff_match"]
        self.has_aff = aux["has_aff"]
        self.has_anti = aux["has_anti"]
        self.n_sel_spread = aux["n_sel_spread"]
        self.kinds: frozenset = kinds
        self.topo_slots: frozenset = topo_slots
        self.overflow = overflow


class TermStage:
    """Content-interned, refcounted slab of encoded term rows."""

    def __init__(self, vocab, capacity: int = MIN_CAPACITY):
        self.vocab = vocab
        self._lock = audited_rlock("terms")
        self._next_gen = 1  # ktpu: guarded-by(self._lock)
        self._next_entry = 0
        # the SelectorSpread getSelectors hook (driver installs the same
        # fn it uses at dispatch): consulted at acquire time so the entry
        # key matches the dispatch-time dedup key
        self.selectors_fn: Optional[Callable] = None
        # bank wake-up hook (TermBankDevice sets it)
        self.on_dirty: Optional[Callable] = None
        # bumped on every rebuild; the device twin keys its full-upload
        # decision on it
        self.generation = 0  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock)
        self.stats: Dict[str, int] = {
            "staged": 0,  # entries encoded (once per distinct term set)
            "hits": 0,  # acquire served by an existing entry
            "overflows": 0,  # slab-full growth events
            "rebuilds": 0,  # capacity/width rebuilds
        }
        self._build(max(capacity, MIN_CAPACITY))

    # -- slab lifecycle ------------------------------------------------------

    # ktpu: holds(self._lock) callers: __init__ (pre-concurrency) and the
    # locked acquire/ensure_current/_rebuild paths
    def _build(self, capacity: int) -> None:
        self.capacity = capacity  # ktpu: guarded-by(self._lock)
        # encode-guard snapshot, the PodStage discipline: a vocab key-slot
        # growth means fresh encodes could name slots the node banks can't
        # index yet — rebuild (all entries stale) and re-encode at the new
        # width. Unlike the pod slab, NO term array is key-slot-wide, so
        # this is an encode-guard refresh, not a shape change.
        self.key_capacity = self.vocab.config.key_slots
        # the row slab: a TermBank used with explicit free-list allocation
        # (named `batch` so the device twin's slab-agnostic uploader —
        # ingest/bank.StageBank — reads it like the pod slab's PodBatch)
        self.batch = TermBank(self.vocab, capacity)  # ktpu: guarded-by(self._lock)
        self._free: List[int] = list(range(capacity - 1, -1, -1))  # ktpu: guarded-by(self._lock)
        self._entry_of: Dict[tuple, int] = {}  # ktpu: guarded-by(self._lock)
        self._entries: Dict[int, TermEntry] = {}  # ktpu: guarded-by(self._lock)
        self.dirty_rows: set = set()  # ktpu: guarded-by(self._lock)
        self.generation += 1
        # gather padding template: an untouched TermBank row, reproduced
        # bit-for-bit on the padded lanes of the index dispatch
        self.empty_rows = TermBank(self.vocab, 1).arrays()

    # ktpu: holds(self._lock) called from acquire/ensure_current only
    def _rebuild(self, capacity: Optional[int] = None) -> None:
        self.stats["rebuilds"] += 1
        self._build(capacity or self.capacity)

    def current_for(self, vocab) -> bool:
        return vocab is self.vocab and self.key_capacity == vocab.config.key_slots

    def ensure_current(self) -> bool:
        """Rebuild if the vocab key width grew. Returns True when a
        rebuild happened (every outstanding (entry, gen) pair is stale)."""
        with self._lock:
            if self.current_for(self.vocab):
                return False
            self._rebuild()
            return True

    # -- entry acquisition ---------------------------------------------------

    # ktpu: holds(self._lock) called from the locked acquire/ensure_entry
    def _encode_entry(self, pod, sels, key) -> Optional[TermEntry]:
        rows_args, aux = encode_pod_terms(pod, sels)
        need = len(rows_args)
        if need > len(self._free):
            self.stats["overflows"] += 1
            grown = max(self.capacity * 2, _bucket(need, MIN_CAPACITY))
            if grown > MAX_CAPACITY:
                return None  # safety valve: legacy path absorbs it
            self._rebuild(grown)  # every outstanding pair goes stale
        bank = self.batch
        rows: List[int] = []
        try:
            for kind, topo, sel, nss, ns_any, weight, sm in rows_args:
                row = self._free.pop()
                bank.clear_row(row)
                bank.overflow_owners.discard(row)
                bank.set_row(
                    row, kind, row, topo, sel, namespaces=nss,
                    ns_any=ns_any, weight=weight, self_match=sm,
                )
                rows.append(row)
        except KeySlotOverflow:
            # vocab key width grew mid-encode: rebuild at the fresh width
            # and let the caller's next admission (or dispatch restage)
            # encode cleanly — the PodStage acquire contract
            self._rebuild()
            return None
        # selector/namespace truncation: the row under/over-matches on
        # device — the owning pod must route through the scalar oracle
        # (terms.TermBank.overflow_owners, keyed here by row)
        overflow = any(r in bank.overflow_owners for r in rows)
        for r in rows:
            bank.overflow_owners.discard(r)
        kinds = frozenset(a[0] for a in rows_args)
        topo_slots = frozenset(
            int(bank.topo_slot[r]) for r in rows if bank.topo_slot[r] >= 0
        )
        gen = self._next_gen
        self._next_gen += 1
        entry = TermEntry(tuple(rows), gen, key, aux, kinds, topo_slots, overflow)
        eid = self._next_entry
        self._next_entry += 1
        self._entry_of[key] = eid
        self._entries[eid] = entry
        self.dirty_rows.update(rows)
        self.stats["staged"] += 1
        cb = self.on_dirty
        if cb is not None:
            cb()  # Event.set — safe under the lock
        return entry

    # ktpu: holds(self._lock) the shared acquire core
    def _acquire(self, pod, sels) -> Optional[Tuple[int, int]]:
        if not self.current_for(self.vocab):
            self._rebuild()
        key = spec_key(pod, sels)
        eid = self._entry_of.get(key)
        if eid is not None:
            e = self._entries[eid]
            e.refs += 1
            self.stats["hits"] += 1
            return eid, e.gen
        e = self._encode_entry(pod, sels, key)
        if e is None:
            return None
        e.refs = 1
        return self._entry_of[key], e.gen

    def acquire(self, pod) -> Optional[Tuple[int, int]]:
        """Intern `pod`'s term set (+1 ref). Returns (entry id, gen), or
        None when the pod cannot be staged right now (encode overflow
        mid-vocab-growth, slab at its ceiling) — the caller schedules it
        via the legacy path and retries staging on the next admission."""
        with self._lock:
            sels = self.selectors_fn(pod) if self.selectors_fn is not None else None
            return self._acquire(pod, sels)

    def ensure_entry(self, pod, selectors=_UNSET) -> Optional[Tuple[int, int]]:
        """Intern WITHOUT taking a reference — the dispatch-time restage
        path. A fresh zero-ref entry is never freed by release() (no
        holder can release it), so it stays valid through the dispatch
        and lingers until a slab rebuild reclaims it — bounded by slab
        capacity, the PodStage.ensure_row contract. `selectors` overrides
        the installed selectors_fn (the driver passes its dispatch-time
        getSelectors result so the entry key matches the batch dedup)."""
        with self._lock:
            sels = (
                (self.selectors_fn(pod) if self.selectors_fn is not None else None)
                if selectors is _UNSET else selectors
            )
            pair = self._acquire(pod, sels)
            if pair is None:
                return None
            eid, gen = pair
            e = self._entries[eid]
            e.refs -= 1
            if e.refs < 0:
                e.refs = 0
            return pair

    def release(self, eid: int, gen: int) -> None:
        """Drop one reference. Frees the entry's rows at zero — a later
        acquire of the same term set re-encodes. Stale pairs are ignored
        (the entry they named is already gone)."""
        with self._lock:
            e = self._entries.get(eid)
            if e is None or e.gen != gen:
                return
            e.refs -= 1
            if e.refs <= 0:
                self._entries.pop(eid, None)
                self._entry_of.pop(e.key, None)
                for r in e.rows:
                    self.batch.valid[r] = False
                    self._free.append(r)
                # freed rows are never gathered (no live pair names them),
                # so the device twin needs no update; content is cleared
                # at re-allocation

    def valid_pair(self, eid: int, gen: int) -> bool:
        with self._lock:
            e = self._entries.get(eid)
            return e is not None and e.gen == gen

    # ktpu: holds(self._lock) callers hold the slab lock (the device-twin
    # parity probe, TermBankDevice via StageBank.device_divergence)
    def live_rows_locked(self) -> List[int]:
        """Row indices currently ALLOCATED (not on the free list) — the
        only rows the gather can read, so the only rows the parity probe
        may compare: freeing an entry leaves its device rows stale by
        design (doc above)."""
        free = set(self._free)
        return [r for r in range(self.capacity) if r not in free]

    def census(self) -> Dict[str, object]:
        """One lock-disciplined snapshot of the term slab's steady-state
        health (obs/introspect): interned entries, row occupancy,
        free-list depth, outstanding refcounts, dirty rows, lifetime
        stats. Counters and metadata only."""
        with self._lock:
            return {
                "enabled": True,
                "capacity": int(self.capacity),
                "rows": int(self.capacity - len(self._free)),
                "free_rows": len(self._free),
                "entries": len(self._entries),
                "refs_total": int(sum(e.refs for e in self._entries.values())),
                "dirty_rows": len(self.dirty_rows),
                "generation": int(self.generation),
                "next_gen": int(self._next_gen),
                "stats": dict(self.stats),
            }

    # ktpu: holds(self._lock) the driver's prologue resolves entries
    # inside its locked capture window
    def entry_for(self, eid: int, gen: int, key) -> Optional[TermEntry]:
        """The dispatch-time validity check: the pair must be live AND
        the entry's intern key must equal the batch's dedup key for this
        rep — a spreading-selector change between enqueue and dispatch
        (service added/removed) makes the entry stale by key mismatch.
        Caller holds the slab lock (the prologue's resolve window)."""
        e = self._entries.get(eid)
        if e is None or e.gen != gen or e.key != key:
            return None
        return e
