"""Term-bank plane: enqueue-time term interning, a device-resident term
bank, and index-only term dispatch — the ingest plane's content-interning
move applied to topology-coupled structure (the InterPodAffinity wall,
ROADMAP item 1)."""

from .bank import TERM_RUNGS, TermBankDevice
from .gather import gather_terms
from .stage import MAX_CAPACITY, MIN_CAPACITY, TermEntry, TermStage

__all__ = [
    "TERM_RUNGS",
    "TermBankDevice",
    "gather_terms",
    "MAX_CAPACITY",
    "MIN_CAPACITY",
    "TermEntry",
    "TermStage",
]
