"""Index-only term dispatch prologue: rebuild a batch's term table ON
DEVICE.

One jitted gather reconstructs the exact per-batch TermBank array dict
the solve/arbiter programs consume, from the resident term slab and two
int32 vectors — slab row per batch-term lane, owning rep per lane — the
only term-side payload a covered dispatch ships. Entries are concatenated
in rep order and each entry's rows sit in the canonical per-pod encode
order (state/terms.encode_pod_terms), so lane i holds EXACTLY what
compile_batch_terms would have written at row i; `owner` (the one
per-batch column) is rewritten from the shipped vector, and padding lanes
reproduce an untouched TermBank row bit-for-bit (`empty` is the slab's
1-row zero-state). Placements are therefore bit-identical to the legacy
host-built path by construction, which the parity suite pins.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# ktpu: admitted(KIND_TERM) every dispatch site (driver._term_prologue,
# WarmupService._warm_term) admits the (t, slab-capacity) pair through
# compile_plan.admit as a KIND_TERM spec before calling — the program is
# planned even though the jit wrapper lives here
@jax.jit
def gather_terms(bank, idx, owner, keep, empty):
    """bank: term slab dict ([S, ...]); idx: [T] int32 slab rows; owner:
    [T] int32 owning rep of each lane; keep: [T] bool (True for real term
    lanes, False for padding); empty: 1-row TermBank dict (the padding
    template). Returns the batch's term-table dict, [T, ...]."""
    out = {}
    for k, v in bank.items():
        g = v[idx]
        cond = keep.reshape((-1,) + (1,) * (g.ndim - 1))
        out[k] = jnp.where(cond, g, empty[k])
    # the slab stores owner = the row's own index; the batch table owns
    # rows by rep position — rewrite from the shipped vector (padding
    # lanes keep the untouched-row owner, 0)
    out["owner"] = jnp.where(keep, owner, 0).astype(jnp.int32)
    return out
