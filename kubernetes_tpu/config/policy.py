"""The legacy Policy API (pkg/scheduler/api/types.go Policy, loadable from
a file or ConfigMap — scheduler.go:352-386 initPolicyFrom*).

JSON shape:
    {"kind": "Policy", "apiVersion": "v1",
     "predicates": [{"name": "PodFitsResources"}, ...],
     "priorities": [{"name": "LeastRequestedPriority", "weight": 1}, ...],
     "extenders": [{"urlPrefix": ..., "filterVerb": ..., ...}],
     "hardPodAffinitySymmetricWeight": 1}

Empty predicate/priority lists mean "use the defaults" only when the key
is ABSENT; an explicitly empty list means none (factory.go:304-381
CreateFromConfig semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..extender.client import ExtenderConfig
from .provider import KNOWN_PREDICATES, KNOWN_PRIORITIES, default_predicates, default_priorities

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50  # api/types.go:29 (moot: full matrix)


class PolicyError(ValueError):
    pass


@dataclass
class Policy:
    predicates: Optional[frozenset] = None  # None = defaults
    priorities: Optional[Tuple[Tuple[str, int], ...]] = None
    extenders: List[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT


def _extender_from_json(d: dict) -> ExtenderConfig:
    return ExtenderConfig(
        url_prefix=d.get("urlPrefix", ""),
        filter_verb=d.get("filterVerb", ""),
        prioritize_verb=d.get("prioritizeVerb", ""),
        bind_verb=d.get("bindVerb", ""),
        preempt_verb=d.get("preemptVerb", ""),
        weight=int(d.get("weight", 1)),
        node_cache_capable=bool(d.get("nodeCacheCapable", False)),
        ignorable=bool(d.get("ignorable", False)),
        managed_resources=[
            r.get("name", "") for r in d.get("managedResources") or []
        ],
        timeout_s=float(d.get("httpTimeout", 5.0)),
    )


def parse_policy(obj: dict) -> Policy:
    if obj.get("kind") not in (None, "Policy"):
        raise PolicyError(f"not a Policy: kind={obj.get('kind')!r}")
    policy = Policy()
    # Go json semantics: an ABSENT or NULL slice means "use defaults"; only
    # an explicitly-empty list means none (factory.go CreateFromConfig)
    if obj.get("predicates") is not None:
        names = set()
        for p in obj["predicates"] or []:
            name = p.get("name", "")
            if name not in KNOWN_PREDICATES:
                raise PolicyError(f"unknown predicate {name!r}")
            names.add(name)
        policy.predicates = frozenset(names)
    else:
        policy.predicates = default_predicates()
    if obj.get("priorities") is not None:
        pairs = []
        for p in obj["priorities"] or []:
            name = p.get("name", "")
            if name not in KNOWN_PRIORITIES:
                raise PolicyError(f"unknown priority {name!r}")
            weight = int(p.get("weight", 1))
            if weight < 0:
                raise PolicyError(f"negative weight for {name}")
            pairs.append((name, weight))
        policy.priorities = tuple(pairs)
    else:
        policy.priorities = default_priorities()
    policy.extenders = [_extender_from_json(e) for e in obj.get("extenders") or []]
    w = obj.get("hardPodAffinitySymmetricWeight")
    if w is not None:
        if not (0 <= int(w) <= 100):
            raise PolicyError("hardPodAffinitySymmetricWeight must be in [0, 100]")
        policy.hard_pod_affinity_symmetric_weight = int(w)
    return policy
