"""The legacy Policy API (pkg/scheduler/api/types.go Policy, loadable from
a file or ConfigMap — scheduler.go:352-386 initPolicyFrom*).

JSON shape:
    {"kind": "Policy", "apiVersion": "v1",
     "predicates": [{"name": "PodFitsResources"}, ...],
     "priorities": [{"name": "LeastRequestedPriority", "weight": 1}, ...],
     "extenders": [{"urlPrefix": ..., "filterVerb": ..., ...}],
     "hardPodAffinitySymmetricWeight": 1}

Empty predicate/priority lists mean "use the defaults" only when the key
is ABSENT; an explicitly empty list means none (factory.go:304-381
CreateFromConfig semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..extender.client import ExtenderConfig
from .provider import KNOWN_PREDICATES, KNOWN_PRIORITIES, default_predicates, default_priorities

DEFAULT_HARD_POD_AFFINITY_WEIGHT = 1
DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE = 50  # api/types.go:29 (moot: full matrix)


class PolicyError(ValueError):
    pass


@dataclass
class Policy:
    predicates: Optional[frozenset] = None  # None = defaults
    priorities: Optional[Tuple[Tuple[str, int], ...]] = None
    extenders: List[ExtenderConfig] = field(default_factory=list)
    hard_pod_affinity_symmetric_weight: int = DEFAULT_HARD_POD_AFFINITY_WEIGHT
    # requestedToCapacityRatioArguments, when a priority entry supplies it
    # (api/types.go:139-152): (shape points (utilization, score), resource
    # weights (name, weight))
    rtcr: Optional[Tuple[Tuple[Tuple[int, int], ...], Tuple[Tuple[str, int], ...]]] = None
    # Custom-argument predicates/priorities (api/types.go:83-137) — run as
    # framework Filter/Score plugins over the host commit path (the factory
    # builds them; RegisterCustomFitPredicate plugins.go:127 semantics):
    #   ("CheckNodeLabelPresence", name, labels, presence)
    #   ("ServiceAffinity",        name, labels)
    custom_predicates: Tuple[tuple, ...] = ()
    #   ("NodeLabel",           name, weight, label, presence)
    #   ("ServiceAntiAffinity", name, weight, label)
    custom_priorities: Tuple[tuple, ...] = ()


def _extender_from_json(d: dict) -> ExtenderConfig:
    return ExtenderConfig(
        url_prefix=d.get("urlPrefix", ""),
        filter_verb=d.get("filterVerb", ""),
        prioritize_verb=d.get("prioritizeVerb", ""),
        bind_verb=d.get("bindVerb", ""),
        preempt_verb=d.get("preemptVerb", ""),
        weight=int(d.get("weight", 1)),
        node_cache_capable=bool(d.get("nodeCacheCapable", False)),
        ignorable=bool(d.get("ignorable", False)),
        managed_resources=[
            r.get("name", "") for r in d.get("managedResources") or []
        ],
        timeout_s=float(d.get("httpTimeout", 5.0)),
    )


def _parse_rtcr_arguments(d: dict):
    """RequestedToCapacityRatioArguments (api/types.go:139-152 →
    buildScoringFunctionShapeFromRequestedToCapacityRatioArguments,
    plugins.go:416-438): shape points validated by NewFunctionShape; empty
    resources default to cpu/memory weight 1; zero weights become 1."""
    from ..oracle.priorities import validate_function_shape

    shape = tuple(
        (int(pt.get("utilization", 0)), int(pt.get("score", 0)))
        for pt in d.get("shape") or []
    )
    try:
        validate_function_shape(shape)
    except ValueError as e:
        raise PolicyError(f"invalid RequestedToCapacityRatio arguments: {e}")
    res = d.get("resources") or []
    if not res:
        resources = (("cpu", 1), ("memory", 1))
    else:
        for r in res:
            if int(r.get("weight", 0)) < 0:
                raise PolicyError(
                    f"RequestedToCapacityRatio resource {r.get('name')!r} "
                    "weight must not be negative"
                )
        # an omitted/zero weight becomes 1 (plugins.go:432-435)
        resources = tuple(
            (r.get("name", ""), int(r.get("weight", 0)) or 1) for r in res
        )
    for rname, _ in resources:
        if rname not in ("cpu", "memory"):
            raise PolicyError(
                f"RequestedToCapacityRatio resource {rname!r} not supported by "
                "the device score path (cpu/memory only)"
            )
    return shape, resources


def parse_policy(obj: dict) -> Policy:
    if obj.get("kind") not in (None, "Policy"):
        raise PolicyError(f"not a Policy: kind={obj.get('kind')!r}")
    policy = Policy()
    # Go json semantics: an ABSENT or NULL slice means "use defaults"; only
    # an explicitly-empty list means none (factory.go CreateFromConfig)
    if obj.get("predicates") is not None:
        names = set()
        custom_preds = []
        for p in obj["predicates"] or []:
            name = p.get("name", "")
            arg = p.get("argument") or {}
            lp = arg.get("labelsPresence")
            sa = arg.get("serviceAffinity")
            if lp is not None:
                # RegisterCustomFitPredicate (plugins.go:127): user-named
                # CheckNodeLabelPresence instance
                custom_preds.append((
                    "CheckNodeLabelPresence",
                    name,
                    tuple(lp.get("labels") or []),
                    bool(lp.get("presence", False)),
                ))
                continue
            if sa is not None:
                custom_preds.append((
                    "ServiceAffinity",
                    name,
                    tuple(sa.get("labels") or []),
                ))
                continue
            if name not in KNOWN_PREDICATES:
                raise PolicyError(f"unknown predicate {name!r}")
            names.add(name)
        policy.predicates = frozenset(names)
        policy.custom_predicates = tuple(custom_preds)
    else:
        policy.predicates = default_predicates()
    if obj.get("priorities") is not None:
        pairs = []
        custom_pris = []
        for p in obj["priorities"] or []:
            name = p.get("name", "")
            weight = int(p.get("weight", 1))
            if weight < 0:
                raise PolicyError(f"negative weight for {name}")
            arg = p.get("argument") or {}
            lpref = arg.get("labelPreference")
            saa = arg.get("serviceAntiAffinity")
            if lpref is not None:
                custom_pris.append((
                    "NodeLabel",
                    name,
                    weight,
                    lpref.get("label", ""),
                    bool(lpref.get("presence", False)),
                ))
                continue
            if saa is not None:
                custom_pris.append((
                    "ServiceAntiAffinity",
                    name,
                    weight,
                    saa.get("label", ""),
                ))
                continue
            rtcr_args = arg.get("requestedToCapacityRatioArguments")
            if rtcr_args is not None:
                # custom priority carrying its own name; register it under
                # the canonical kernel name (plugins.go:389-393 builds an
                # RTCR function for whatever name the Policy chose). Only ONE
                # such entry is representable — a second would silently
                # shadow the first's shape, so reject it.
                if policy.rtcr is not None:
                    raise PolicyError(
                        "multiple priorities with requestedToCapacityRatioArguments"
                    )
                policy.rtcr = _parse_rtcr_arguments(rtcr_args)
                pairs.append(("RequestedToCapacityRatioPriority", weight))
                continue
            if name not in KNOWN_PRIORITIES:
                raise PolicyError(f"unknown priority {name!r}")
            pairs.append((name, weight))
        policy.priorities = tuple(pairs)
        policy.custom_priorities = tuple(custom_pris)
    else:
        policy.priorities = default_priorities()
    policy.extenders = [_extender_from_json(e) for e in obj.get("extenders") or []]
    w = obj.get("hardPodAffinitySymmetricWeight")
    if w is not None:
        if not (0 <= int(w) <= 100):
            raise PolicyError("hardPodAffinitySymmetricWeight must be in [0, 100]")
        policy.hard_pod_affinity_symmetric_weight = int(w)
    return policy
