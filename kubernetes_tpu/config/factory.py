"""Configurator (pkg/scheduler/factory/factory.go:133): translate a
Provider name, Policy, or ComponentConfig into a configured Scheduler.

CreateFromProvider (:294) / CreateFromConfig (:304) / CreateFromKeys
(:382) semantics: the chosen predicate/priority sets become (a) a
SolveConfig statically gating the fused device kernels, (b) the oracle
predicate chain's enabled set (threaded via PredicateMetadata), (c) the
volume checker's row selection, and (d) HTTPExtender clients.
"""

from __future__ import annotations

import json
from typing import Callable, List, Optional, Tuple

from ..extender.client import ExtenderConfig, HTTPExtender
from ..ops.pipeline import SolveConfig
from ..scheduler.driver import Scheduler
from ..utils.featuregate import FeatureGate
from ..volume.predicates import make_volume_checker
from .componentconfig import KubeSchedulerConfiguration
from .policy import Policy, parse_policy
from .provider import VOLUME_PREDICATES, get_provider


class Configurator:
    def __init__(
        self,
        feature_gates: Optional[FeatureGate] = None,
        pvc_lister: Optional[Callable] = None,
        pv_lister: Optional[Callable] = None,
        sc_lister: Optional[Callable] = None,
        csinode_lister: Optional[Callable] = None,
        volume_binder=None,
        service_lister: Optional[Callable] = None,
        **scheduler_kwargs,
    ):
        self.feature_gates = feature_gates or FeatureGate()
        self.pvc_lister = pvc_lister
        self.pv_lister = pv_lister
        self.sc_lister = sc_lister
        self.csinode_lister = csinode_lister
        self.volume_binder = volume_binder
        self.service_lister = service_lister
        self.scheduler_kwargs = scheduler_kwargs

    def create_from_provider(self, name: str = "DefaultProvider") -> Scheduler:
        predicates, priorities = get_provider(name, self.feature_gates)
        return self.create_from_keys(predicates, priorities, [])

    def create_from_config(self, policy) -> Scheduler:
        """policy: a Policy, a parsed JSON dict, or a JSON string."""
        if isinstance(policy, str):
            policy = json.loads(policy)
        if isinstance(policy, dict):
            policy = parse_policy(policy)
        assert isinstance(policy, Policy)
        return self.create_from_keys(
            policy.predicates,
            policy.priorities,
            policy.extenders,
            rtcr=policy.rtcr,
            custom_predicates=policy.custom_predicates,
            custom_priorities=policy.custom_priorities,
        )

    def create_from_component_config(self, cfg: KubeSchedulerConfiguration) -> Scheduler:
        if cfg.feature_gates:
            self.feature_gates.set_from_map(cfg.feature_gates)
        if cfg.policy_file:
            with open(cfg.policy_file) as f:
                return self.create_from_config(json.load(f))
        return self.create_from_provider(cfg.algorithm_provider or "DefaultProvider")

    def create_from_keys(
        self,
        predicates: Optional[frozenset],
        priorities: Optional[Tuple[Tuple[str, int], ...]],
        extender_configs: List[ExtenderConfig],
        rtcr=None,
        custom_predicates: Tuple[tuple, ...] = (),
        custom_priorities: Tuple[tuple, ...] = (),
    ) -> Scheduler:
        from .provider import default_predicates, default_priorities

        if predicates is None:
            predicates = default_predicates(self.feature_gates)
        if priorities is None:
            priorities = default_priorities(self.feature_gates)
        solve_config = SolveConfig(
            predicates=frozenset(predicates), priorities=tuple(priorities), rtcr=rtcr
        )
        volume_checker = None
        wanted_volume = frozenset(predicates) & VOLUME_PREDICATES
        if wanted_volume and self.pvc_lister is not None and self.pv_lister is not None:
            volume_checker = make_volume_checker(
                self.pvc_lister,
                self.pv_lister,
                sc_lister=self.sc_lister,
                csinode_lister=self.csinode_lister,
                binder=self.volume_binder if "CheckVolumeBinding" in predicates else None,
                enabled=wanted_volume,
            )
        extenders = [HTTPExtender(c) for c in extender_configs]
        sched = Scheduler(
            solve_config=solve_config,
            volume_checker=volume_checker,
            volume_binder=self.volume_binder,
            extenders=extenders,
            **self.scheduler_kwargs,
        )
        if custom_predicates or custom_priorities:
            # EXTEND the scheduler's framework (a caller-supplied one came
            # through scheduler_kwargs and already wired queue-sort) — the
            # policy shims implement no QueueSort, so appending is safe
            sched.framework.plugins.extend(
                self._build_custom_plugins(sched, custom_predicates, custom_priorities)
            )
        return sched

    def _build_custom_plugins(self, sched, custom_predicates, custom_priorities):
        """Policy custom-argument predicates/priorities → framework plugins
        over the host commit path (RegisterCustomFitPredicate /
        RegisterCustomPriorityFunction, factory/plugins.go:127,363). The
        device mask can't host user-named predicates as jit statics; the
        framework already forces host filtering when Filter plugins exist."""
        from ..framework.plugins.builtin import (
            Handle,
            ServiceAffinityPlugin,
            predicate_plugin,
            priority_plugin,
        )
        from ..oracle.predicates import check_node_label_presence
        from ..oracle.priorities import node_label_priority, service_anti_affinity_priority

        services = self.service_lister or (lambda: [])
        snap = lambda: sched.cache.snapshot
        plugins = []
        for spec in custom_predicates:
            kind = spec[0]
            if kind == "CheckNodeLabelPresence":
                _, name, labels, presence = spec
                plugins.append(predicate_plugin(
                    name,
                    lambda pod, ni, _l=labels, _p=presence: check_node_label_presence(
                        pod, ni, _l, _p
                    ),
                    msg="node(s) didn't have the requested labels",
                ))
            elif kind == "ServiceAffinity":
                _, name, labels = spec
                plugins.append(ServiceAffinityPlugin(name, labels, snap, services))
        handle = Handle(snap)
        for spec in custom_priorities:
            kind = spec[0]
            if kind == "NodeLabel":
                _, name, weight, label, presence = spec
                plugins.append(priority_plugin(
                    name,
                    lambda pod, s, _l=label, _p=presence: node_label_priority(pod, s, _l, _p),
                    handle,
                    weight=weight,
                ))
            elif kind == "ServiceAntiAffinity":
                _, name, weight, label = spec
                plugins.append(priority_plugin(
                    name,
                    lambda pod, s, _l=label: service_anti_affinity_priority(
                        pod, s, _l, services()
                    ),
                    handle,
                    weight=weight,
                ))
        return plugins
