"""Algorithm providers: named default predicate/priority sets
(pkg/scheduler/algorithmprovider/defaults/defaults.go).

The 1.16 effective defaults: TaintNodesByCondition is GA, so the
node-condition predicates are already replaced by PodToleratesNodeTaints +
CheckNodeUnschedulable (ApplyFeatureGates, defaults.go:63-90); the
EvenPodsSpread gate adds its predicate + priority (defaults.go:94-103).

ClusterAutoscalerProvider = default with MostRequested replacing
LeastRequested (defaults.go:33-37).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..utils.featuregate import DEFAULT_FEATURE_GATE, FeatureGate

# volume predicate registration names → handled by volume.make_volume_checker
VOLUME_PREDICATES = frozenset(
    {
        "NoVolumeZoneConflict",
        "MaxEBSVolumeCount",
        "MaxGCEPDVolumeCount",
        "MaxAzureDiskVolumeCount",
        "MaxCSIVolumeCountPred",
        "NoDiskConflict",
        "CheckVolumeBinding",
    }
)

# device/oracle predicate names (predicates.go:56-110)
CORE_PREDICATES = frozenset(
    {
        "CheckNodeUnschedulable",
        "GeneralPredicates",
        "HostName",
        "PodFitsHostPorts",
        "MatchNodeSelector",
        "PodFitsResources",
        "PodToleratesNodeTaints",
        "MatchInterPodAffinity",
        "EvenPodsSpread",
    }
)

KNOWN_PREDICATES = CORE_PREDICATES | VOLUME_PREDICATES

KNOWN_PRIORITIES = frozenset(
    {
        "EqualPriority",
        "LeastRequestedPriority",
        "MostRequestedPriority",
        "BalancedResourceAllocation",
        "NodeAffinityPriority",
        "TaintTolerationPriority",
        "NodePreferAvoidPodsPriority",
        "ImageLocalityPriority",
        "SelectorSpreadPriority",
        "InterPodAffinityPriority",
        "EvenPodsSpreadPriority",
        # feature-gated (ResourceLimits, defaults.go:106-111)
        "ResourceLimitsPriority",
        # Policy-argument custom priority (plugins.go:389-393); the
        # registration name used when a Policy supplies
        # requestedToCapacityRatioArguments
        "RequestedToCapacityRatioPriority",
    }
)


def default_predicates(fg: Optional[FeatureGate] = None) -> frozenset:
    fg = fg or DEFAULT_FEATURE_GATE
    preds = {
        "NoVolumeZoneConflict",
        "MaxEBSVolumeCount",
        "MaxGCEPDVolumeCount",
        "MaxAzureDiskVolumeCount",
        "MaxCSIVolumeCountPred",
        "MatchInterPodAffinity",
        "NoDiskConflict",
        "GeneralPredicates",
        "PodToleratesNodeTaints",
        "CheckVolumeBinding",
        # TaintNodesByCondition GA replacement (defaults.go:63-90)
        "CheckNodeUnschedulable",
    }
    if fg.enabled("EvenPodsSpread"):
        preds.add("EvenPodsSpread")
    return frozenset(preds)


def default_priorities(fg: Optional[FeatureGate] = None) -> Tuple[Tuple[str, int], ...]:
    fg = fg or DEFAULT_FEATURE_GATE
    pairs = [
        ("SelectorSpreadPriority", 1),
        ("InterPodAffinityPriority", 1),
        ("LeastRequestedPriority", 1),
        ("BalancedResourceAllocation", 1),
        ("NodePreferAvoidPodsPriority", 10000),
        ("NodeAffinityPriority", 1),
        ("TaintTolerationPriority", 1),
        ("ImageLocalityPriority", 1),
    ]
    if fg.enabled("EvenPodsSpread"):
        pairs.append(("EvenPodsSpreadPriority", 1))
    if fg.enabled("ResourceLimits"):
        # ResourceLimitsPriorityFunction gate (defaults.go:106-111)
        pairs.append(("ResourceLimitsPriority", 1))
    return tuple(pairs)


def cluster_autoscaler_predicates(fg: Optional[FeatureGate] = None) -> frozenset:
    return default_predicates(fg)


def cluster_autoscaler_priorities(fg: Optional[FeatureGate] = None) -> Tuple[Tuple[str, int], ...]:
    return tuple(
        (("MostRequestedPriority", w) if n == "LeastRequestedPriority" else (n, w))
        for n, w in default_priorities(fg)
    )


PROVIDERS: Dict[str, Dict[str, object]] = {
    "DefaultProvider": {
        "predicates": default_predicates,
        "priorities": default_priorities,
    },
    "ClusterAutoscalerProvider": {
        "predicates": cluster_autoscaler_predicates,
        "priorities": cluster_autoscaler_priorities,
    },
}


def get_provider(name: str, fg: Optional[FeatureGate] = None):
    """→ (predicates frozenset, priorities tuple). KeyError on unknown."""
    p = PROVIDERS[name]
    return p["predicates"](fg), p["priorities"](fg)
