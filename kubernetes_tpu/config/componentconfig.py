"""KubeSchedulerConfiguration (pkg/scheduler/apis/config/types.go; staged
copy staging/src/k8s.io/kube-scheduler).

JSON shape (v1alpha1):
    {"apiVersion": "kubescheduler.config.k8s.io/v1alpha1",
     "kind": "KubeSchedulerConfiguration",
     "schedulerName": "default-scheduler",
     "algorithmSource": {"provider": "DefaultProvider"}
                        | {"policy": {"file": {"path": "..."}}},
     "percentageOfNodesToScore": 50,
     "bindTimeoutSeconds": 600,
     "leaderElection": {"leaderElect": true, "leaseDuration": "15s", ...},
     "metricsBindAddress": "127.0.0.1:10251",
     "featureGates": {"EvenPodsSpread": true}}
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional

from .policy import DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE


def _parse_duration(v, default_s: float) -> float:
    """Go duration → seconds: '15s', '2m', '1m30s', '1h2m3.5s', or a bare
    number."""
    if v is None:
        return default_s
    if isinstance(v, (int, float)):
        return float(v)
    import re

    s = str(v).strip()
    units = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}
    parts = re.findall(r"([0-9]*\.?[0-9]+)(ms|s|m|h)", s)
    if parts and "".join(n + u for n, u in parts) == s:
        return sum(float(n) * units[u] for n, u in parts)
    return float(s)


@dataclass
class LeaderElectionConfig:
    leader_elect: bool = False
    lease_duration_s: float = 15.0
    renew_deadline_s: float = 10.0
    retry_period_s: float = 2.0
    resource_name: str = "kube-scheduler"
    resource_namespace: str = "kube-system"


@dataclass
class KubeSchedulerConfiguration:
    scheduler_name: str = "default-scheduler"
    algorithm_provider: Optional[str] = "DefaultProvider"
    policy_file: Optional[str] = None
    percentage_of_nodes_to_score: int = DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE
    bind_timeout_seconds: float = 600.0
    metrics_bind_address: str = ""
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    feature_gates: Dict[str, bool] = field(default_factory=dict)


def parse_component_config(obj: dict) -> KubeSchedulerConfiguration:
    cfg = KubeSchedulerConfiguration()
    cfg.scheduler_name = obj.get("schedulerName", cfg.scheduler_name)
    src = obj.get("algorithmSource") or {}
    if "policy" in src and src["policy"]:
        cfg.algorithm_provider = None
        f = (src["policy"].get("file") or {}).get("path")
        cfg.policy_file = f
    elif "provider" in src and src["provider"]:
        cfg.algorithm_provider = src["provider"]
    cfg.percentage_of_nodes_to_score = int(
        obj.get("percentageOfNodesToScore", cfg.percentage_of_nodes_to_score)
    )
    cfg.bind_timeout_seconds = float(obj.get("bindTimeoutSeconds", cfg.bind_timeout_seconds))
    cfg.metrics_bind_address = obj.get("metricsBindAddress", "")
    le = obj.get("leaderElection") or {}
    cfg.leader_election = LeaderElectionConfig(
        leader_elect=bool(le.get("leaderElect", False)),
        lease_duration_s=_parse_duration(le.get("leaseDuration"), 15.0),
        renew_deadline_s=_parse_duration(le.get("renewDeadline"), 10.0),
        retry_period_s=_parse_duration(le.get("retryPeriod"), 2.0),
        resource_name=le.get("resourceName", "kube-scheduler"),
        resource_namespace=le.get("resourceNamespace", "kube-system"),
    )
    cfg.feature_gates = dict(obj.get("featureGates") or {})
    return cfg


def load_component_config(path: str) -> KubeSchedulerConfiguration:
    with open(path) as f:
        return parse_component_config(json.load(f))
