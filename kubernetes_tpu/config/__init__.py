"""Config surface: Policy API, ComponentConfig, algorithm providers,
Configurator factory (pkg/scheduler/{api,apis/config,algorithmprovider,
factory})."""

from .componentconfig import (
    KubeSchedulerConfiguration,
    LeaderElectionConfig,
    load_component_config,
    parse_component_config,
)
from .factory import Configurator
from .policy import (
    DEFAULT_HARD_POD_AFFINITY_WEIGHT,
    DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE,
    Policy,
    PolicyError,
    parse_policy,
)
from .provider import (
    KNOWN_PREDICATES,
    KNOWN_PRIORITIES,
    PROVIDERS,
    VOLUME_PREDICATES,
    default_predicates,
    default_priorities,
    get_provider,
)

__all__ = [
    "KubeSchedulerConfiguration",
    "LeaderElectionConfig",
    "load_component_config",
    "parse_component_config",
    "Configurator",
    "DEFAULT_HARD_POD_AFFINITY_WEIGHT",
    "DEFAULT_PERCENTAGE_OF_NODES_TO_SCORE",
    "Policy",
    "PolicyError",
    "parse_policy",
    "KNOWN_PREDICATES",
    "KNOWN_PRIORITIES",
    "PROVIDERS",
    "VOLUME_PREDICATES",
    "default_predicates",
    "default_priorities",
    "get_provider",
]
