"""Runtime lock-order/race harness — the dynamic complement of KTPU003.

The static guarded-by pass proves accesses sit under the RIGHT lock; it
cannot see the ORDER two threads take two locks in. This harness can:
with ``KTPU_LOCK_AUDIT=1`` every lock the package constructs through the
``audited_*`` factories is wrapped, each acquisition while other locks
are held records a directed edge (held → acquired) with the acquiring
thread and call site, and ``assert_acyclic()`` fails the test run when
the edge graph contains a cycle — the ABBA pattern that deadlocks the
informer / uploader / commit-worker / warmup thread quartet.

Zero overhead when the env var is unset: the factories return plain
``threading`` primitives.

The audited wrappers deliberately key edges by lock NAME (one name per
lock ROLE — "queue", "stage", "cache", ...), not instance: the invariant
worth enforcing is a global ordering between roles, exactly like
kube-scheduler's documented cache→queue ordering. Reentrant acquisition
of the SAME instance records nothing; two instances of one role nested
inside each other DO record a self-edge — nesting peers of a role is an
ordering hazard unless some global order exists.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV_FLAG = "KTPU_LOCK_AUDIT"


def audit_enabled() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false", "False")


class RoleAuditViolation(AssertionError):
    """Raised by assert_roles_subset(): the observed thread-role →
    lock-role graph escaped the static inference (roles.py), or the
    observed graph is empty (the role registrations were unwired)."""


class LockOrderViolation(AssertionError):
    """Raised by assert_acyclic(): carries the offending cycle(s)."""

    def __init__(self, cycles: List[List[str]], registry: "LockOrderRegistry"):
        self.cycles = cycles
        lines = ["lock-order cycle(s) detected (potential ABBA deadlock):"]
        for cyc in cycles:
            lines.append("  cycle: " + " -> ".join(cyc + [cyc[0]]))
            for a, b in zip(cyc, cyc[1:] + [cyc[0]]):
                w = registry.edges.get((a, b))
                if w:
                    lines.append(
                        f"    {a} -> {b}: thread={w['thread']} at {w['site']}"
                    )
        super().__init__("\n".join(lines))


class LockOrderRegistry:
    """Process-global edge graph. Thread-safe via one internal lock (a
    plain lock — the registry itself is outside the audited world)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._held = threading.local()  # per-thread [(name, inst_id), ...]
        # (from_name, to_name) -> first witness {thread, site}
        self.edges: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.threads_seen: set = set()
        self.acquisitions = 0
        # thread-role audit (the runtime twin of analysis/roles.py):
        # threads register their role at spawn; acquisitions from a
        # registered thread record role -> lock-role observations. The
        # role itself lives in a threading.local — per-thread state by
        # construction, immune to OS thread-ident recycling (an
        # ident-keyed dict would hand a dead bind worker's role to
        # whichever new thread inherits its ident) and lock-free to
        # read/re-stamp on the hot path.
        self._role = threading.local()
        self.roles_seen: set = set()  # ktpu: guarded-by(self._mu)
        self.lock_roles: Dict[str, set] = {}  # ktpu: guarded-by(self._mu)

    # -- thread-role registration (runtime twin of roles.py) -----------------

    def register_role(self, role: str) -> None:
        """Stamp the CURRENT thread's role. Spawn sites call this at the
        top of their thread target (or a pool initializer); the driver
        stamps itself at schedule/warmup entry. Idempotent re-stamps
        (every schedule_batch, every submitted closure) are one
        thread-local read — no global lock. Last registration wins (a
        supervisor thread becomes the driver when it drives
        schedule_batch)."""
        if getattr(self._role, "value", None) == role:
            return
        self._role.value = role
        with self._mu:
            self.roles_seen.add(role)

    def current_role(self) -> Optional[str]:
        return getattr(self._role, "value", None)

    def observed_roles(self) -> Dict[str, set]:
        with self._mu:
            return {k: set(v) for k, v in self.lock_roles.items()}

    def assert_roles_subset(
        self,
        static: Dict[str, set],
        min_distinct_roles: int = 2,
    ) -> None:
        """The soundness probe: every (lock role, thread role) pair the
        audit OBSERVED must be contained in the STATIC inference
        (roles.static_lock_roles) — `"*"` entries are role-universal by
        declaration. Also fails on an empty/degenerate observed graph:
        silently unwiring register_role must fail exactly like the
        non-empty-edge assertion on the ordering audit."""
        observed = self.observed_roles()
        distinct = set()
        for rs in observed.values():
            distinct |= rs
        if not observed or len(distinct) < min_distinct_roles:
            raise RoleAuditViolation(
                "observed role graph is empty or degenerate "
                f"(locks={sorted(observed)}, roles={sorted(distinct)}) — "
                "the register_role spawn-site stamps are no longer wired"
            )
        bad = []
        for lock, rs in sorted(observed.items()):
            allowed = static.get(lock, set())
            if "*" in allowed:
                continue
            for role in sorted(rs):
                if role not in allowed:
                    bad.append((lock, role, sorted(allowed)))
        if bad:
            lines = [
                "runtime thread-role observations escaped the static "
                "role inference (static analysis is UNSOUND here — fix "
                "the role seeds/resolution, not this assertion):"
            ]
            for lock, role, allowed in bad:
                lines.append(
                    f"  lock role '{lock}' touched by thread role "
                    f"'{role}' but statically reachable only by {allowed}"
                )
            raise RoleAuditViolation("\n".join(lines))

    # -- held bookkeeping ----------------------------------------------------

    def _stack(self) -> List[Tuple[str, int]]:
        if not hasattr(self._held, "locks"):
            self._held.locks = []
        return self._held.locks

    @staticmethod
    def _site() -> str:
        for frame in reversed(traceback.extract_stack(limit=16)):
            if "lockorder" not in (frame.filename or ""):
                return f"{os.path.basename(frame.filename)}:{frame.lineno} in {frame.name}"
        return "?"

    def note_acquired(self, name: str, inst_id: int) -> None:
        held = self._stack()
        tname = threading.current_thread().name
        role = getattr(self._role, "value", None)  # this thread's own slot
        with self._mu:
            self.acquisitions += 1
            self.threads_seen.add(tname)
            if role is not None:
                self.lock_roles.setdefault(name, set()).add(role)
            if any(i == inst_id for _, i in held):
                pass  # reentrant: no new edge, no new held entry depth
            else:
                site = None
                for hname, hinst in held:
                    if hinst == inst_id:
                        continue
                    key = (hname, name)
                    if key not in self.edges:
                        site = site or self._site()
                        self.edges[key] = {"thread": tname, "site": site}
        held.append((name, inst_id))

    def note_released(self, name: str, inst_id: int) -> None:
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == (name, inst_id):
                del held[i]
                return

    # -- analysis ------------------------------------------------------------

    def find_cycles(self) -> List[List[str]]:
        graph: Dict[str, List[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, []).append(b)
        cycles: List[List[str]] = []
        seen_cycles: set = set()
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(u: str) -> None:
            color[u] = GRAY
            stack.append(u)
            for v in graph.get(u, ()):  # noqa: B023
                if color.get(v, WHITE) == WHITE:
                    dfs(v)
                elif color.get(v) == GRAY:
                    cyc = stack[stack.index(v):]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(list(cyc))
            stack.pop()
            color[u] = BLACK

        for node in sorted(graph):
            if color.get(node, WHITE) == WHITE:
                dfs(node)
        return cycles

    def assert_acyclic(self) -> None:
        cycles = self.find_cycles()
        if cycles:
            # flight-recorder black box: a lock-order cycle is exactly the
            # "invisible mid-drain" bug class the cycle ring exists for —
            # dump it before raising. Lazy import + module-level hook so
            # the diagnostic layer never re-enters the audited lock world
            # (and obs/ stays import-free of analysis/).
            try:
                from ..obs.recorder import blackbox_dump_hook

                blackbox_dump_hook("lock-order-violation")
            except Exception:
                pass  # the violation must surface even if the dump cannot
            raise LockOrderViolation(cycles, self)

    def report(self) -> Dict:
        with self._mu:
            return {
                "edges": {
                    f"{a} -> {b}": dict(w) for (a, b), w in sorted(self.edges.items())
                },
                "threads": sorted(self.threads_seen),
                "acquisitions": self.acquisitions,
                "cycles": self.find_cycles(),
                "roles": sorted(self.roles_seen),
                "lock_roles": {
                    k: sorted(v) for k, v in sorted(self.lock_roles.items())
                },
            }

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()
            self.threads_seen.clear()
            self.acquisitions = 0
            self.roles_seen.clear()
            self.lock_roles.clear()
        # per-thread role slots persist (a live registered thread keeps
        # its identity across a registry reset — only OBSERVATIONS reset)


REGISTRY = LockOrderRegistry()


# ---------------------------------------------------------------------------
# audited primitives
# ---------------------------------------------------------------------------

class _AuditedBase:
    def __init__(self, name: str, inner):
        self._name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            REGISTRY.note_acquired(self._name, id(self))
        return ok

    def release(self) -> None:
        self._inner.release()
        REGISTRY.note_released(self._name, id(self))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class AuditedLock(_AuditedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.Lock())


class AuditedRLock(_AuditedBase):
    def __init__(self, name: str):
        super().__init__(name, threading.RLock())

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        raise NotImplementedError


class AuditedCondition:
    """threading.Condition twin over an audited lock. wait() drops the
    lock from the held set for its duration — a blocked waiter holds
    nothing and must not contribute ordering edges."""

    def __init__(self, name: str, lock: Optional[_AuditedBase] = None):
        # default inner is an RLock, matching threading.Condition() — the
        # audited and unaudited worlds must have identical reentrancy
        # semantics or enabling the audit changes what deadlocks
        self._alock = lock or AuditedRLock(name)
        # built directly over the audited lock's raw inner primitive so
        # Condition's __init__-time method bindings (_is_owned,
        # _release_save, ...) refer to the lock actually being held
        self._cond = threading.Condition(self._alock._inner)
        self._name = name

    def acquire(self, *a, **kw):
        ok = self._alock._inner.acquire(*a, **kw)
        if ok:
            REGISTRY.note_acquired(self._name, id(self))
        return ok

    def release(self):
        self._alock._inner.release()
        REGISTRY.note_released(self._name, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def wait(self, timeout: Optional[float] = None):
        REGISTRY.note_released(self._name, id(self))
        try:
            return self._cond.wait(timeout)
        finally:
            REGISTRY.note_acquired(self._name, id(self))

    def wait_for(self, predicate, timeout: Optional[float] = None):
        REGISTRY.note_released(self._name, id(self))
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            REGISTRY.note_acquired(self._name, id(self))

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()


# ---------------------------------------------------------------------------
# construction-site factories (the package's lock sites call these)
# ---------------------------------------------------------------------------

def register_thread_role(role: str) -> None:
    """Stamp the current thread's role for the runtime role audit. Every
    spawn site calls this unconditionally (one dict write — audit-off
    runs pay nothing else: plain locks never consult the registry)."""
    REGISTRY.register_role(role)


def audited_lock(name: str) -> threading.Lock:
    """A Lock, audited iff KTPU_LOCK_AUDIT is set at construction time."""
    return AuditedLock(name) if audit_enabled() else threading.Lock()


def audited_rlock(name: str) -> threading.RLock:
    return AuditedRLock(name) if audit_enabled() else threading.RLock()


def audited_condition(name: str) -> threading.Condition:
    return AuditedCondition(name) if audit_enabled() else threading.Condition()
