"""ktpu-lint infrastructure: module loading, annotations, baseline.

The checkers (analysis/checkers.py) are pure functions over a
``ModuleInfo`` — parsed AST + source lines + the ``# ktpu:`` annotation
map — and yield ``Violation`` records. This module owns everything rule-
independent:

* **Annotations** — one comment grammar for the whole toolchain::

      # ktpu: guarded-by(self._lock)      attr assigned here is shared
      # ktpu: holds(self._lock)           def runs with the lock held
      # ktpu: confined(driver)            attr/def belongs to ONE thread
      # ktpu: hot-path                    def is dispatch/arbiter/fold code
      # ktpu: admitted(KIND_FOLD)         jit here is a planned program
      # ktpu: donates(0, 1)               def donates these positional args
      # ktpu: host-sync-ok <reason>       deliberate device→host sync point
      # ktpu: allow(KTPU001) <reason>     suppress a rule on this line
      # ktpu: thread-entry(<role>)        def/spawn-site executed by that
                                          thread role (seeds roles.py)

  Multiple markers may share a line, separated by ``;``.

* **Baseline** — pre-existing violations are checked in with a
  justification; the tree-wide scan fails closed only when the violation
  SET GROWS. Fingerprints are line-number-free (rule | path | scope |
  detail) so unrelated edits don't churn the file.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: every rule the registry knows; checkers register against these ids
RULES = {
    "KTPU001": "no-unplanned-jit",
    "KTPU002": "donation-safety",
    "KTPU003": "guarded-by",
    "KTPU004": "hot-path-host-sync",
    "KTPU005": "shadowed-module-import",
    "KTPU006": "shared-attr-inference",
    "KTPU007": "transitive-hot-path-sync",
    "KTPU008": "confinement-reachability",
}

_MARKER_RE = re.compile(r"#\s*ktpu:\s*(.+?)\s*$")
_ITEM_RE = re.compile(
    r"(?P<kind>guarded-by|holds|confined|hot-path|admitted|donates"
    r"|host-sync-ok|allow|thread-entry)"
    r"\s*(?:\((?P<args>[^)]*)\))?\s*(?P<trail>[^;]*)"
)


@dataclass(frozen=True)
class Violation:
    rule: str  # "KTPU001"
    path: str  # repo-relative posix path
    line: int  # 1-based
    scope: str  # dotted qualname of enclosing class/function ("" = module)
    detail: str  # short, stable description (part of the fingerprint)
    message: str  # full human message

    def fingerprint(self) -> str:
        """Line-number-free identity used by the baseline."""
        return f"{self.rule}|{self.path}|{self.scope}|{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} "
            f"[{RULES.get(self.rule, '?')}] {self.message}"
        )


@dataclass
class Annotation:
    kind: str  # guarded-by | holds | confined | hot-path | admitted | donates | host-sync-ok | allow
    args: Tuple[str, ...] = ()
    reason: str = ""


def parse_annotations(lines: Sequence[str]) -> Dict[int, List[Annotation]]:
    """Line (1-based) → parsed ``# ktpu:`` markers on that line."""
    out: Dict[int, List[Annotation]] = {}
    for i, raw in enumerate(lines, start=1):
        if "ktpu:" not in raw:
            continue
        m = _MARKER_RE.search(raw)
        if m is None:
            continue
        items: List[Annotation] = []
        for part in m.group(1).split(";"):
            part = part.strip()
            if not part:
                continue
            im = _ITEM_RE.match(part)
            if im is None:
                continue
            args = tuple(
                a.strip() for a in (im.group("args") or "").split(",") if a.strip()
            )
            items.append(
                Annotation(
                    kind=im.group("kind"),
                    args=args,
                    reason=(im.group("trail") or "").strip(),
                )
            )
        if items:
            out[i] = items
    return out


@dataclass
class ModuleInfo:
    """Everything a checker needs about one source file."""

    path: str  # absolute
    relpath: str  # repo-relative posix path (fingerprint stable)
    source: str
    lines: List[str]
    tree: ast.AST
    annotations: Dict[int, List[Annotation]]
    #: ast node -> parent node (lexical), for with-block / scope walks
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    # -- annotation helpers --------------------------------------------------

    def marks(self, line: int, kind: str) -> List[Annotation]:
        return [a for a in self.annotations.get(line, []) if a.kind == kind]

    def comment_block_lines(self, line: int) -> List[int]:
        """`line` plus the contiguous comment block directly above it —
        THE one definition of where a marker may sit relative to a
        statement (node_marks, allowed, and roles._line_marks all build
        on this; a tweak here keeps the grammar consistent everywhere)."""
        out = [line]
        ln = line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("#"):
            out.append(ln)
            ln -= 1
        return out

    def node_marks(self, node: ast.AST, kind: str) -> List[Annotation]:
        """Markers on any line the node's header spans (its lineno, plus —
        for defs — the decorator lines and the contiguous comment block
        immediately above, where a standalone marker reads naturally)."""
        lines = {getattr(node, "lineno", 0)}
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            for dec in node.decorator_list:
                lines.add(dec.lineno)
            first = min(lines - {0}) if lines - {0} else 0
            if first:
                lines.update(self.comment_block_lines(first)[1:])
        out: List[Annotation] = []
        for ln in lines:
            out.extend(self.annotations.get(ln, []) or [])
        return [a for a in out if a.kind == kind]

    def allowed(self, node: ast.AST, rule: str) -> bool:
        """``# ktpu: allow(KTPUxxx)`` on the node's line or anywhere in
        the contiguous comment block directly above it (multi-line
        justifications read naturally that way, same as node_marks)."""
        for probe in self.comment_block_lines(getattr(node, "lineno", 0)):
            for a in self.marks(probe, "allow"):
                if rule in a.args or not a.args:
                    return True
        return False

    # -- scope helpers -------------------------------------------------------

    def qualname(self, node: ast.AST) -> str:
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_functions(self, node: ast.AST):
        """All enclosing function defs, innermost first."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None

    def with_locks_around(self, node: ast.AST) -> Set[str]:
        """Normalized source of every ``with X:`` context expression
        lexically enclosing the node."""
        out: Set[str] = set()
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    out.add(normalize_expr(ast.unparse(item.context_expr)))
            cur = self.parents.get(cur)
        return out


def normalize_expr(s: str) -> str:
    return re.sub(r"\s+", "", s)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class AnalysisConfig:
    """Per-rule policy knobs. `repo_config()` (checkers.py) builds the
    tree's canonical instance; tests build narrow ones for fixtures."""

    # KTPU001: modules (relpath prefixes) where jit construction is the
    # module's JOB (kernel factories, the compile plan, the shard_map shim)
    jit_allowed_prefixes: Tuple[str, ...] = ()
    # KTPU002b/KTPU004: modules holding mirror-resident / sharded banks
    surface_prefixes: Tuple[str, ...] = ()
    # KTPU002b: designated sync points — "Class.method" or "function"
    sync_allowlist: Tuple[str, ...] = ()
    # KTPU002b/KTPU004: name components that mark device-resident values
    # (this repo's convention: device twins always carry `dev` — _dev,
    # _dev_nodes, na_dev, score_dev, ... — or say device/resident outright;
    # host-side banks are named nodes/eps/pats/batch/bank and never match)
    device_name_re: str = r"(^|_)dev(_|$)|device|resident"

    def is_jit_allowed_module(self, relpath: str) -> bool:
        return any(relpath.startswith(p) for p in self.jit_allowed_prefixes)

    def is_surface_module(self, relpath: str) -> bool:
        return any(relpath.startswith(p) for p in self.surface_prefixes)

    def device_like(self, dotted: str) -> bool:
        pat = re.compile(self.device_name_re)
        return any(pat.search(part) for part in dotted.split("."))


# ---------------------------------------------------------------------------
# walking + running
# ---------------------------------------------------------------------------

def iter_python_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames if d not in ("__pycache__", ".git")
        )
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def load_module(path: str, repo_root: str) -> ModuleInfo:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
    return ModuleInfo(
        path=path,
        relpath=rel,
        source=source,
        lines=source.splitlines(),
        tree=ast.parse(source, filename=path),
        annotations=parse_annotations(source.splitlines()),
    )


Checker = Callable[[ModuleInfo, AnalysisConfig], List[Violation]]


def run_checkers(
    mod: ModuleInfo,
    config: AnalysisConfig,
    checkers: Sequence[Checker],
    rules: Optional[Set[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    import time as _time

    out: List[Violation] = []
    for chk in checkers:
        t0 = _time.perf_counter()
        found = chk(mod, config)
        if timings is not None:
            # checkers carry a `rule` tag (checkers.py); the wall of the
            # two KTPU002 passes aggregates under one rule id
            key = getattr(chk, "rule", chk.__name__)
            timings[key] = timings.get(key, 0.0) + _time.perf_counter() - t0
        for v in found:
            if rules and v.rule not in rules:
                continue
            out.append(v)
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def scan_paths(
    paths: Sequence[str],
    repo_root: str,
    config: AnalysisConfig,
    checkers: Sequence[Checker],
    rules: Optional[Set[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(iter_python_files(p))
        else:
            files.append(p)
    out: List[Violation] = []
    for f in files:
        out.extend(
            run_checkers(load_module(f, repo_root), config, checkers, rules, timings)
        )
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

class Baseline:
    """Line-oriented fingerprint set. Grammar per line::

        <fingerprint>  # <justification>

    '#'-only and blank lines are comments. ``--check`` fails on any
    violation whose fingerprint is absent (the set GREW); fingerprints
    with no live violation are reported as stale (ratchet down) but do
    not fail."""

    def __init__(self, entries: Optional[Dict[str, str]] = None):
        self.entries: Dict[str, str] = entries or {}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        entries: Dict[str, str] = {}
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as f:
                for raw in f:
                    line = raw.strip()
                    if not line or line.startswith("#"):
                        continue
                    fp, _, justification = line.partition("#")
                    fp = fp.strip()
                    if fp:
                        entries[fp] = justification.strip()
        return cls(entries)

    def save(self, path: str, violations: Sequence[Violation]) -> None:
        with open(path, "w", encoding="utf-8") as f:
            f.write(
                "# ktpu-lint baseline — pre-existing violations, each with a\n"
                "# justification. The tree scan fails only when a violation\n"
                "# NOT listed here appears (the set grew). Regenerate with\n"
                "#   python scripts/ktpu_lint.py --update-baseline\n"
                "# which preserves justifications for surviving entries.\n"
            )
            for v in sorted({x.fingerprint() for x in violations}):
                note = self.entries.get(v, "JUSTIFY ME")
                f.write(f"{v}  # {note}\n")

    def missing(self, violations: Sequence[Violation]) -> List[Violation]:
        return [v for v in violations if v.fingerprint() not in self.entries]

    def stale(self, violations: Sequence[Violation]) -> List[str]:
        live = {v.fingerprint() for v in violations}
        return sorted(fp for fp in self.entries if fp not in live)
