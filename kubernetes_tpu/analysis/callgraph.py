"""Repo-wide call graph: the interprocedural substrate of KTPU006–008.

The module-local checkers (checkers.py) see one file at a time; the
thread-role rules need to know *who can call what across the whole
package* — an unannotated attribute written on the uploader thread and
read on the driver is invisible module-locally, and a hot-path function
that forces a host sync one call deep is invisible to KTPU004. This
module builds the conservative call graph those rules walk:

* **functions** — every ``def`` in every scanned module, keyed by a
  stable uid ``<relpath>::<qualname>`` (nested defs included: a bind
  closure submitted to a pool is its own node);
* **classes** — name, bases (resolved through imports), own methods,
  and an *attribute type map* inferred from ``__init__``/class-body
  assignments (``self.x = ClassName(...)``, ``self.x = param`` with an
  annotated param, ``self.x: T``), so ``self.queue.pop_batch()``
  resolves to ``PriorityQueue.pop_batch`` instead of every ``pop_batch``
  in the tree;
* **edges** — caller → callee, each tagged ``direct`` (module function,
  ``self.method`` dispatch through the class hierarchy, typed-receiver
  method, resolved import) or ``fuzzy`` (name-only method match, used
  as a last resort for *distinctive* names — see ``_FUZZY_BLOCKLIST``).

Resolution is deliberately conservative in the sound direction for role
propagation: ``self.m()`` dispatches to ``m`` anywhere in the class's
hierarchy (ancestors AND repo subclasses — the receiver may be any of
them), a typed receiver includes subclass overrides, and a class call
edges to every ``__init__`` on its MRO. Where the graph cannot resolve
(callbacks stored in attributes, ``Thread(target=...)`` indirection),
the ``# ktpu: thread-entry`` seed grammar in roles.py closes the gap —
and the runtime role audit (lockorder.assert_roles_subset) is the
soundness probe that catches anything both of them miss.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import ModuleInfo, dotted_name, load_module

#: method names too generic for name-only (fuzzy) resolution: linking
#: every `x.get(...)` to every repo class defining `get` would weld the
#: whole graph together. Calls on these either resolve typed or not at
#: all; the runtime role audit exists to catch the "not at all" misses.
_FUZZY_BLOCKLIST = frozenset({
    "get", "set", "add", "pop", "put", "update", "items", "keys", "values",
    "append", "extend", "insert", "remove", "discard", "clear", "copy",
    "count", "index", "sort", "reverse", "join", "split", "strip", "close",
    "start", "stop", "run", "wait", "notify", "notify_all", "acquire",
    "release", "read", "write", "flush", "send", "recv", "encode", "decode",
    "format", "replace", "match", "search", "group", "setdefault",
    "submit", "result", "done", "cancel", "shutdown", "is_set", "list",
    "delete", "create", "name", "key", "keys_view", "exists", "mkdir",
    "lower", "upper", "startswith", "endswith",
})

#: above this many same-name candidates a fuzzy link is noise, not signal
_FUZZY_MAX_TARGETS = 4


@dataclass
class FuncInfo:
    """One function/method/nested def."""

    uid: str  # "<relpath>::<qualname>" — stable across line edits
    relpath: str
    qualname: str  # dotted, as ModuleInfo.qualname renders it
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    mod: ModuleInfo
    cls: Optional["ClassInfo"] = None  # immediate enclosing class, if any
    #: nearest enclosing class even for nested defs (a bind closure's
    #: `self` still means the method's class) — set by _link_classes
    owner_cls: Optional["ClassInfo"] = None


@dataclass
class ClassInfo:
    relpath: str
    name: str
    node: ast.ClassDef
    mod: ModuleInfo
    base_names: List[str] = field(default_factory=list)  # as written
    bases: List["ClassInfo"] = field(default_factory=list)  # resolved
    subclasses: List["ClassInfo"] = field(default_factory=list)
    methods: Dict[str, FuncInfo] = field(default_factory=dict)  # own only
    #: attr -> ClassInfo (inferred instance type of self.<attr>)
    attr_types: Dict[str, "ClassInfo"] = field(default_factory=dict)
    #: attr -> set of lock ROLE names (audited_lock("x") ctor sites +
    #: aliases like `self._lock = stage._lock`); sets because a subclass
    #: may rebind the aliased source (PodStage "stage" vs TermStage "terms")
    lock_attrs: Dict[str, Set[str]] = field(default_factory=dict)

    @property
    def key(self) -> Tuple[str, str]:
        return (self.relpath, self.name)

    def mro_like(self) -> List["ClassInfo"]:
        """self + resolved ancestors, breadth-first (good enough for
        attribute/method lookup; diamonds just mean both branches)."""
        out: List[ClassInfo] = []
        frontier = [self]
        seen: Set[Tuple[str, str]] = set()
        while frontier:
            c = frontier.pop(0)
            if c.key in seen:
                continue
            seen.add(c.key)
            out.append(c)
            frontier.extend(c.bases)
        return out

    def family(self) -> List["ClassInfo"]:
        """self + ancestors + all transitive repo subclasses — every
        class an instance at a ``self.m()`` call site might be."""
        out = {c.key: c for c in self.mro_like()}
        frontier = [self]
        while frontier:
            c = frontier.pop(0)
            for s in c.subclasses:
                if s.key not in out:
                    out[s.key] = s
                    frontier.append(s)
        return list(out.values())

    def find_method(self, name: str) -> List[FuncInfo]:
        """`name` looked up over the family: the ancestors supply the
        inherited implementation, the subclasses the overrides."""
        hits: List[FuncInfo] = []
        for c in self.family():
            fi = c.methods.get(name)
            if fi is not None:
                hits.append(fi)
        return hits


@dataclass
class Edge:
    src: str  # FuncInfo.uid
    dst: str
    kind: str  # "direct" | "fuzzy"
    line: int


class RepoGraph:
    """The package-wide index + call edges."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}  # relpath -> info
        self.functions: Dict[str, FuncInfo] = {}  # uid -> info
        self.classes: Dict[Tuple[str, str], ClassInfo] = {}
        self.class_by_name: Dict[str, List[ClassInfo]] = {}
        self.methods_by_name: Dict[str, List[FuncInfo]] = {}
        self.module_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        #: relpath -> alias -> ("module", relpath) | ("symbol", relpath,
        #: name) | ("external", dotted)
        self.imports: Dict[str, Dict[str, Tuple]] = {}
        #: relpath -> module-level var name -> ClassInfo
        self.module_var_types: Dict[str, Dict[str, ClassInfo]] = {}
        self.edges: Dict[str, List[Edge]] = {}
        self._edge_seen: Set[Tuple[str, str, str]] = set()
        #: func ast node -> uid (innermost-def attribution for walks)
        self.node_uid: Dict[int, str] = {}

    # -- queries -------------------------------------------------------------

    def callees(self, uid: str, fuzzy: bool = True) -> List[Edge]:
        es = self.edges.get(uid, [])
        return es if fuzzy else [e for e in es if e.kind == "direct"]

    def function_for_node(self, mod: ModuleInfo, node: ast.AST) -> Optional[FuncInfo]:
        """The innermost enclosing def's FuncInfo for an arbitrary node."""
        fn = node if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            else mod.enclosing_function(node)
        if fn is None:
            return None
        return self.functions.get(self.node_uid.get(id(fn), ""))

    def resolve_class_name(self, relpath: str, name: str) -> Optional[ClassInfo]:
        """`name` as visible from module `relpath` (local class or
        imported symbol), falling back to a unique global name."""
        mod_imports = self.imports.get(relpath, {})
        head = name.split(".")[0]
        tgt = mod_imports.get(head)
        if tgt is not None:
            if tgt[0] == "symbol":
                ci = self.classes.get((tgt[1], tgt[2]))
                if ci is not None:
                    return ci
            elif tgt[0] == "module" and "." in name:
                ci = self.classes.get((tgt[1], name.split(".", 1)[1]))
                if ci is not None:
                    return ci
            return None
        ci = self.classes.get((relpath, head))
        if ci is not None:
            return ci
        cands = self.class_by_name.get(head, [])
        return cands[0] if len(cands) == 1 else None

    # -- construction --------------------------------------------------------

    def add_edge(self, src: str, dst: str, kind: str, line: int) -> None:
        key = (src, dst, kind)
        if src == dst or key in self._edge_seen:
            return
        self._edge_seen.add(key)
        self.edges.setdefault(src, []).append(Edge(src, dst, kind, line))


# ---------------------------------------------------------------------------
# import resolution
# ---------------------------------------------------------------------------

def _module_relpath_candidates(dotted: str) -> List[str]:
    p = dotted.replace(".", "/")
    return [p + ".py", p + "/__init__.py"]


def _resolve_imports(mods: Dict[str, ModuleInfo]) -> Dict[str, Dict[str, Tuple]]:
    known = set(mods)
    out: Dict[str, Dict[str, Tuple]] = {}
    for rel, mod in mods.items():
        table: Dict[str, Tuple] = {}
        # package dirs of this module; for pkg/__init__.py the package
        # IS the containing dir, so the same dirname expression holds
        pkg_parts = rel.split("/")[:-1]
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = None
                    for cand in _module_relpath_candidates(
                        a.name if a.asname else a.name.split(".")[0]
                    ):
                        if cand in known:
                            target = ("module", cand)
                            break
                    table[alias] = target or ("external", a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                else:
                    base = []
                stem = "/".join(base + (node.module or "").split("."))
                stem = stem.strip("/").replace("//", "/")
                mod_rel = None
                for cand in (stem + ".py", stem + "/__init__.py"):
                    if cand in known:
                        mod_rel = cand
                        break
                for a in node.names:
                    alias = a.asname or a.name
                    if mod_rel is not None:
                        # the symbol may itself be a submodule
                        sub = None
                        if mod_rel.endswith("/__init__.py"):
                            subbase = mod_rel[: -len("__init__.py")] + a.name
                            for cand in (subbase + ".py", subbase + "/__init__.py"):
                                if cand in known:
                                    sub = cand
                                    break
                        if sub is not None:
                            table[alias] = ("module", sub)
                        else:
                            table[alias] = ("symbol", mod_rel, a.name)
                    else:
                        table[alias] = ("external", f"{node.module}.{a.name}")
        out[rel] = table
    return out


# ---------------------------------------------------------------------------
# type inference helpers
# ---------------------------------------------------------------------------

_LOCK_FACTORIES = {"audited_lock", "audited_rlock", "audited_condition"}


def _annotation_class(graph: RepoGraph, relpath: str, ann: ast.AST) -> Optional[ClassInfo]:
    """ClassInfo for a (possibly quoted / Optional-wrapped) annotation."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        # Optional[X]/Union[X, ...] unwrap to their first class operand;
        # container annotations (List[X], Dict[K, V], ...) deliberately
        # resolve to nothing — the RECEIVER of a call is the container,
        # not its element, so typing the attr as the element class would
        # fabricate edges (x.append resolving to Worker.append, etc.)
        head = dotted_name(ann.value) or ""
        if head.split(".")[-1] in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                inner = inner.elts[0] if inner.elts else None
            return _annotation_class(graph, relpath, inner)
        return None
    nm = dotted_name(ann)
    if nm is None:
        return None
    return graph.resolve_class_name(relpath, nm)


class _TypeEnv:
    """Per-function name → ClassInfo map: annotated params, one-step
    local constructor/param assignments, and (through the closure chain)
    the enclosing functions' locals."""

    def __init__(self, graph: RepoGraph, fi: FuncInfo):
        self.graph = graph
        self.fi = fi
        self.names: Dict[str, ClassInfo] = {}
        chain = [fi.node] + [
            f for f in fi.mod.enclosing_functions(fi.node)
        ]
        # outermost first so inner scopes override
        for fn in reversed(chain):
            self._fill_from(fn)

    def _fill_from(self, fn) -> None:
        graph, rel = self.graph, self.fi.relpath
        args = list(fn.args.posonlyargs) + list(fn.args.args) + list(fn.args.kwonlyargs)
        for a in args:
            ci = _annotation_class(graph, rel, a.annotation)
            if ci is not None:
                self.names[a.arg] = ci
        for node in ast.walk(fn):
            if self.fi.mod.enclosing_function(node) is not fn and node is not fn:
                continue
            tgt_ci = None
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                tgt_ci = _annotation_class(graph, rel, node.annotation)
                if tgt_ci is not None:
                    self.names[node.target.id] = tgt_ci
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                ci = _value_class(graph, rel, node.value, self.names)
                if ci is not None:
                    self.names[node.targets[0].id] = ci


def _value_class(
    graph: RepoGraph,
    relpath: str,
    value: ast.AST,
    env_names: Optional[Dict[str, ClassInfo]] = None,
) -> Optional[ClassInfo]:
    """Inferred class of a simple rhs: ClassName(...), `x or ClassName(...)`,
    or a name with a known type."""
    if isinstance(value, ast.BoolOp):  # `param or Default()` idiom
        for v in value.values:
            ci = _value_class(graph, relpath, v, env_names)
            if ci is not None:
                return ci
        return None
    if isinstance(value, ast.Call):
        nm = dotted_name(value.func)
        if nm is not None:
            return graph.resolve_class_name(relpath, nm)
        return None
    if isinstance(value, ast.Name) and env_names:
        return env_names.get(value.id)
    return None


# ---------------------------------------------------------------------------
# graph construction
# ---------------------------------------------------------------------------

def _index_module(graph: RepoGraph, mod: ModuleInfo) -> None:
    graph.modules[mod.relpath] = mod
    # classes + functions
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef):
            ci = ClassInfo(
                relpath=mod.relpath,
                name=node.name,
                node=node,
                mod=mod,
                base_names=[dotted_name(b) or "" for b in node.bases],
            )
            graph.classes[ci.key] = ci
            graph.class_by_name.setdefault(node.name, []).append(ci)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = mod.qualname(node)
            uid = f"{mod.relpath}::{qual}"
            fi = FuncInfo(
                uid=uid, relpath=mod.relpath, qualname=qual,
                name=node.name, node=node, mod=mod,
            )
            graph.functions[uid] = fi
            graph.node_uid[id(node)] = uid


def _link_classes(graph: RepoGraph) -> None:
    for ci in graph.classes.values():
        for bn in ci.base_names:
            if not bn:
                continue
            base = graph.resolve_class_name(ci.relpath, bn)
            if base is not None and base.key != ci.key:
                ci.bases.append(base)
                base.subclasses.append(ci)
    # attach methods + module functions
    for fi in graph.functions.values():
        encl = fi.mod.parents.get(fi.node)
        if isinstance(encl, ast.ClassDef):
            ci = graph.classes.get((fi.relpath, encl.name))
            if ci is not None:
                fi.cls = ci
                ci.methods[fi.name] = fi
        elif isinstance(encl, ast.Module):
            graph.module_funcs[(fi.relpath, fi.name)] = fi
        owner = fi.mod.enclosing_class(fi.node)
        if owner is not None:
            fi.owner_cls = graph.classes.get((fi.relpath, owner.name))
        graph.methods_by_name.setdefault(fi.name, []).append(fi)


def _infer_attr_types(graph: RepoGraph) -> None:
    """self.<attr> types + lock-role attrs, per class. Two passes so an
    alias (`self._lock = stage._lock`) can read the source class's roles
    regardless of scan order."""
    env_cache: Dict[int, _TypeEnv] = {}  # per-function, not per-assignment

    def env_for(fn) -> Optional[_TypeEnv]:
        uid = graph.node_uid.get(id(fn))
        if uid is None:
            return None
        env = env_cache.get(id(fn))
        if env is None:
            env = env_cache[id(fn)] = _TypeEnv(graph, graph.functions[uid])
        return env

    for ci in graph.classes.values():
        for node in ast.walk(ci.node):
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Attribute):
                t = node.target
                if isinstance(t.value, ast.Name) and t.value.id == "self":
                    ann = _annotation_class(graph, ci.relpath, node.annotation)
                    if ann is not None:
                        ci.attr_types.setdefault(t.attr, ann)
            if not isinstance(node, ast.Assign):
                continue
            fn = ci.mod.enclosing_function(node)
            if fn is None or ci.mod.enclosing_class(node) is not ci.node:
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                # lock construction: self.X = audited_lock("role")
                v = node.value
                if (
                    isinstance(v, ast.Call)
                    and (dotted_name(v.func) or "").split(".")[-1] in _LOCK_FACTORIES
                    and v.args
                    and isinstance(v.args[0], ast.Constant)
                    and isinstance(v.args[0].value, str)
                ):
                    ci.lock_attrs.setdefault(tgt.attr, set()).add(v.args[0].value)
                    continue
                env = env_for(fn)
                ann = None
                if env is not None:
                    ann = _value_class(graph, ci.relpath, v, env.names)
                if ann is not None:
                    ci.attr_types.setdefault(tgt.attr, ann)
    # alias pass: self.X = <typed param>.<attr>
    for ci in graph.classes.values():
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Assign) or ci.mod.enclosing_class(node) is not ci.node:
                continue
            v = node.value
            if not (
                isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name)
            ):
                continue
            fn = ci.mod.enclosing_function(node)
            if fn is None:
                continue
            env = env_for(fn)
            if env is None:
                continue
            src_ci = env.names.get(v.value.id)
            if src_ci is None:
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                # lock alias: union roles assigned to the source attr
                # anywhere in the source class's family (the declared
                # type may be a base; subclasses rebind with other roles)
                roles: Set[str] = set()
                for c in src_ci.family():
                    roles |= c.lock_attrs.get(v.attr, set())
                if roles:
                    ci.lock_attrs.setdefault(tgt.attr, set()).update(roles)
                t = src_ci.attr_types.get(v.attr)
                if t is not None:
                    ci.attr_types.setdefault(tgt.attr, t)


def _expr_class(
    graph: RepoGraph, fi: FuncInfo, env: _TypeEnv, expr: ast.AST
) -> Optional[ClassInfo]:
    """Static class of a receiver expression, walking attribute chains
    through the inferred attr-type maps."""
    if isinstance(expr, ast.Name):
        if expr.id == "self" and fi.owner_cls is not None:
            return fi.owner_cls
        return env.names.get(expr.id)
    if isinstance(expr, ast.Attribute):
        base = _expr_class(graph, fi, env, expr.value)
        if base is not None:
            for c in base.mro_like():
                t = c.attr_types.get(expr.attr)
                if t is not None:
                    return t
            return None
        # module attribute: np.x / M.binding_duration
        nm = dotted_name(expr.value)
        if nm is not None:
            tgt = graph.imports.get(fi.relpath, {}).get(nm.split(".")[0])
            if tgt is not None and tgt[0] == "module":
                return graph.module_var_types.get(tgt[1], {}).get(expr.attr)
        return None
    if isinstance(expr, ast.Call):
        nm = dotted_name(expr.func)
        if nm is not None:
            return graph.resolve_class_name(fi.relpath, nm)
    return None


def _resolve_call(
    graph: RepoGraph, fi: FuncInfo, env: _TypeEnv, call: ast.Call
) -> List[Tuple[FuncInfo, str]]:
    """(callee, kind) pairs for one Call node."""
    out: List[Tuple[FuncInfo, str]] = []
    f = call.func
    if isinstance(f, ast.Name):
        # nested def / sibling nested def in an enclosing function
        for encl in [fi.node] + fi.mod.enclosing_functions(fi.node):
            for sub in ast.walk(encl):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.name == f.id
                    and sub is not fi.node
                ):
                    uid = graph.node_uid.get(id(sub))
                    if uid:
                        out.append((graph.functions[uid], "direct"))
            if out:
                return out
        tgt = graph.imports.get(fi.relpath, {}).get(f.id)
        if tgt is not None and tgt[0] == "symbol":
            mfi = graph.module_funcs.get((tgt[1], tgt[2]))
            if mfi is not None:
                return [(mfi, "direct")]
            ci = graph.classes.get((tgt[1], tgt[2]))
            if ci is not None:
                return [(m, "direct") for m in ci.find_method("__init__")]
            return []
        mfi = graph.module_funcs.get((fi.relpath, f.id))
        if mfi is not None:
            return [(mfi, "direct")]
        ci = graph.classes.get((fi.relpath, f.id))
        if ci is not None:
            return [(m, "direct") for m in ci.find_method("__init__")]
        return []
    if not isinstance(f, ast.Attribute):
        return []
    # receiver-typed resolution
    recv_ci = _expr_class(graph, fi, env, f.value)
    if recv_ci is not None:
        hits = recv_ci.find_method(f.attr)
        if hits:
            return [(m, "direct") for m in hits]
        return []
    # module-function resolution: alias.func(...)
    nm = dotted_name(f.value)
    if nm is not None:
        tgt = graph.imports.get(fi.relpath, {}).get(nm.split(".")[0])
        if tgt is not None:
            if tgt[0] == "module":
                mfi = graph.module_funcs.get((tgt[1], f.attr))
                if mfi is not None:
                    return [(mfi, "direct")]
                ci = graph.classes.get((tgt[1], f.attr))
                if ci is not None:
                    return [(m, "direct") for m in ci.find_method("__init__")]
                return []
            if tgt[0] == "external":
                return []
    # fuzzy: name-only, distinctive names with few candidates
    if f.attr in _FUZZY_BLOCKLIST or f.attr.startswith("__"):
        return []
    cands = [m for m in graph.methods_by_name.get(f.attr, []) if m.cls is not None]
    if 0 < len(cands) <= _FUZZY_MAX_TARGETS:
        return [(m, "fuzzy") for m in cands]
    return []


def _build_edges(graph: RepoGraph) -> None:
    for fi in graph.functions.values():
        env = _TypeEnv(graph, fi)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            owner = graph.function_for_node(fi.mod, node)
            if owner is None or owner.uid != fi.uid:
                continue  # belongs to a nested def — attributed there
            for callee, kind in _resolve_call(graph, fi, env, node):
                graph.add_edge(fi.uid, callee.uid, kind, node.lineno)


def _infer_module_var_types(graph: RepoGraph) -> None:
    for rel, mod in graph.modules.items():
        table: Dict[str, ClassInfo] = {}
        for node in getattr(mod.tree, "body", []):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
                node.targets[0], ast.Name
            ):
                ci = _value_class(graph, rel, node.value)
                if ci is not None:
                    table[node.targets[0].id] = ci
        graph.module_var_types[rel] = table


def build_graph(mods: Sequence[ModuleInfo]) -> RepoGraph:
    graph = RepoGraph()
    for mod in mods:
        _index_module(graph, mod)
    graph.imports = _resolve_imports(graph.modules)
    _link_classes(graph)
    _infer_module_var_types(graph)
    _infer_attr_types(graph)
    _build_edges(graph)
    return graph


#: one-build-per-process memo for the canonical tree graph: the source
#: tree does not change mid-process, and three consumers (the tree-gate
#: test, the perf_smoke role probes, repeated scans) would otherwise
#: each pay the ~seconds-scale build. Keyed by the resolved path set.
_GRAPH_CACHE: Dict[Tuple, "RepoGraph"] = {}


def load_graph(
    paths: Iterable[str], repo_root: str, cached: bool = True
) -> RepoGraph:
    """Parse every .py under `paths` and build the graph. The result is
    memoized per (path set, root) — graphs are read-only after build;
    pass cached=False when scanning files being rewritten in-process."""
    from .core import iter_python_files

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(iter_python_files(p))
        else:
            files.append(p)
    key = (tuple(sorted(os.path.abspath(f) for f in files)),
           os.path.abspath(repo_root))
    if cached and key in _GRAPH_CACHE:
        return _GRAPH_CACHE[key]
    mods = []
    for f in files:
        try:
            mods.append(load_module(f, repo_root))
        except SyntaxError:
            continue  # not this analysis's job to gate parseability
    graph = build_graph(mods)
    if cached:
        _GRAPH_CACHE[key] = graph
    return graph
