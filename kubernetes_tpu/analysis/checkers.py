"""The KTPU rule set. Each checker: (ModuleInfo, AnalysisConfig) -> [Violation].

Rules and the bugs they are the static twin of (full cross-reference in
INVARIANTS.md):

  KTPU001 no-unplanned-jit        PR 4's invisible mid-drain patch-program
                                  compiles; PR 2's post-commit term-kind miss
  KTPU002 donation-safety         PR 4's np.asarray on a sharded resident
                                  array caching _npy_value → blocked donation
  KTPU003 guarded-by              PR 5's unlocked vocab-slot interning once
                                  encodes moved to the informer thread
  KTPU004 hot-path-host-sync      every PERF round's silent device→host
                                  round-trip on the dispatch/arbiter/fold path
  KTPU005 shadowed-module-import  the seed UnboundLocalError (shadowed
                                  _bucket import broke warmup)
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (
    AnalysisConfig,
    ModuleInfo,
    Violation,
    dotted_name,
    normalize_expr,
)

# ---------------------------------------------------------------------------
# KTPU001 — no-unplanned-jit
# ---------------------------------------------------------------------------

_JIT_ATTRS = {"jit", "pjit", "shard_map"}
_JIT_NAMES = {"jit", "pjit", "shard_map"}


def _jit_refs(mod: ModuleInfo):
    """Every Name/Attribute reference to a jit-constructing callable.
    Import statements don't produce Name nodes, so importing is free —
    only *construction* (calls, decorators, partial(...) args) is seen."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute) and node.attr in _JIT_ATTRS:
            yield node, ast.unparse(node)
        elif isinstance(node, ast.Name) and node.id in _JIT_NAMES:
            # skip the Name inside `jax.jit`-style chains (the Attribute
            # already reported) — a bare Name ref only counts when it is
            # not the .value of a reported Attribute
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Attribute) and parent.attr in _JIT_ATTRS:
                continue
            yield node, node.id


def check_ktpu001(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    if config.is_jit_allowed_module(mod.relpath):
        return []
    out: List[Violation] = []
    for node, text in _jit_refs(mod):
        if mod.allowed(node, "KTPU001"):
            continue
        admitted = False
        for fn in mod.enclosing_functions(node):
            if mod.node_marks(fn, "admitted"):
                admitted = True
                break
            # factory bodies that route through the compile plan are
            # self-evidently planned: they reference a KIND_* spec or
            # call plan.admit/declare in the same scope
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Name) and sub.id.startswith("KIND_"):
                    admitted = True
                    break
                if isinstance(sub, ast.Attribute) and sub.attr.startswith("KIND_"):
                    admitted = True
                    break
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("admit", "declare")
                ):
                    admitted = True
                    break
            if admitted:
                break
        if admitted:
            continue
        scope = mod.qualname(node)
        out.append(
            Violation(
                rule="KTPU001",
                path=mod.relpath,
                line=node.lineno,
                scope=scope,
                detail=text,
                message=(
                    f"`{text}` constructed outside compile/ or an ops/ "
                    "kernel factory, with no KIND_* spec or plan.admit in "
                    "scope — this program is invisible to the compile plan "
                    "and will compile mid-drain. Route it through a "
                    "SolveSpec, or mark the factory "
                    "`# ktpu: admitted(KIND_X)` naming the spec kind that "
                    "covers it."
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# KTPU002 — donation-safety
# ---------------------------------------------------------------------------

def _donated_positions_from_call(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """(positions) when `call` is jax.jit/partial(jax.jit, ...) carrying
    donate_argnums."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                pos = tuple(
                    e.value
                    for e in v.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, int)
                )
                return pos or ()
            return ()  # dynamic: positions unknown — treat all as donated
    return None


def _collect_donating(mod: ModuleInfo) -> Dict[str, Optional[Tuple[int, ...]]]:
    """name -> donated positional indices (None = all args suspect).
    Sources: @partial(jax.jit, donate_argnums=...) decorations,
    `f = jax.jit(g, donate_argnums=...)` bindings, and explicit
    `# ktpu: donates(i, j)` def annotations."""
    donating: Dict[str, Optional[Tuple[int, ...]]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for mark in mod.node_marks(node, "donates"):
                pos = tuple(int(a) for a in mark.args if a.lstrip("-").isdigit())
                donating[node.name] = pos or None
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    pos = _donated_positions_from_call(dec)
                    if pos is not None:
                        donating[node.name] = pos or None
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions_from_call(node.value)
            if pos is not None:
                for tgt in node.targets:
                    nm = dotted_name(tgt)
                    if nm:
                        donating[nm.split(".")[-1]] = pos or None
    return donating


def _scope_body(mod: ModuleInfo, node: ast.AST) -> ast.AST:
    fn = mod.enclosing_function(node)
    return fn if fn is not None else mod.tree


def check_ktpu002_donation(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    """A name passed through a donated argument position may not be read
    again in the same scope (the buffer is deleted); rebinding it (the
    idiomatic `banks = fold(banks, ...)`) ends the taint."""
    donating = _collect_donating(mod)
    if not donating:
        return []
    out: List[Violation] = []
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        callee = dotted_name(call.func)
        if callee is None:
            continue
        positions = donating.get(callee.split(".")[-1], "absent")
        if positions == "absent":
            continue
        if mod.allowed(call, "KTPU002"):
            continue
        donated_args: List[str] = []
        for i, arg in enumerate(call.args):
            if positions is not None and i not in positions:
                continue
            nm = dotted_name(arg)
            if nm is not None:
                donated_args.append(nm)
        if not donated_args:
            continue
        scope = _scope_body(mod, call)
        end = getattr(call, "end_lineno", call.lineno)
        for nm in donated_args:
            # first rebind of the exact name after (or at) the call —
            # `x = f(x)` rebinds on the call line itself
            rebind = None
            for sub in ast.walk(scope):
                if (
                    isinstance(sub, (ast.Name, ast.Attribute))
                    and isinstance(getattr(sub, "ctx", None), ast.Store)
                    and dotted_name(sub) == nm
                    and sub.lineno >= call.lineno
                ):
                    rebind = min(rebind or sub.lineno, sub.lineno)
            for sub in ast.walk(scope):
                if not isinstance(sub, (ast.Name, ast.Attribute)):
                    continue
                if not isinstance(getattr(sub, "ctx", None), ast.Load):
                    continue
                if dotted_name(sub) != nm:
                    continue
                if sub.lineno <= end:
                    continue
                if rebind is not None and sub.lineno > rebind:
                    continue
                if mod.allowed(sub, "KTPU002"):
                    continue
                out.append(
                    Violation(
                        rule="KTPU002",
                        path=mod.relpath,
                        line=sub.lineno,
                        scope=mod.qualname(sub),
                        detail=f"use-after-donate:{nm}->{callee}",
                        message=(
                            f"`{nm}` was donated to `{callee}` (its buffer "
                            "is deleted on dispatch) and is read again "
                            "here — rebind the result to the same name or "
                            "stop reading the stale reference."
                        ),
                    )
                )
                break  # one report per donated name per call
    return out


_FORCING_FUNCS = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.device_get",
}
_ALWAYS_FORCING_ATTRS = {"block_until_ready"}
_VALUE_FORCING_ATTRS = {"item", "tolist"}
_SCALAR_FORCING = {"float", "int"}


def _forcing_target(call: ast.Call) -> Optional[Tuple[ast.AST, str, bool]]:
    """(target expr, callee text, always_forcing) when `call` is a
    device→host forcing construct."""
    f = call.func
    nm = dotted_name(f)
    if nm in _FORCING_FUNCS and call.args:
        return call.args[0], nm, nm == "jax.device_get"
    if isinstance(f, ast.Name) and f.id in _SCALAR_FORCING and call.args:
        return call.args[0], f.id, False
    if isinstance(f, ast.Attribute):
        if f.attr in _ALWAYS_FORCING_ATTRS:
            return f.value, f.attr, True
        if f.attr in _VALUE_FORCING_ATTRS:
            return f.value, f.attr, False
    return None


#: reading these never forces a transfer — shape/dtype probes are free
_METADATA_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "sharding"}


def _through_metadata(mod: ModuleInfo, node: ast.AST, stop: ast.AST) -> bool:
    """True when `node` is only reached via .shape/.dtype/... within the
    expression rooted at `stop` (e.g. int(na_dev["x"].shape[1]))."""
    if node is stop:
        return False
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Attribute) and cur.attr in _METADATA_ATTRS:
            return True
        if cur is stop:
            return False
        cur = mod.parents.get(cur)
    return False


def _device_like_subtree(mod: ModuleInfo, config: AnalysisConfig, node: ast.AST) -> Optional[str]:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Attribute)):
            nm = dotted_name(sub)
            if nm and config.device_like(nm) and not _through_metadata(mod, sub, node):
                return nm
    return None


def _sync_exempt(mod: ModuleInfo, config: AnalysisConfig, call: ast.Call) -> bool:
    if mod.allowed(call, "KTPU002") or mod.marks(call.lineno, "host-sync-ok"):
        return True
    for fn in mod.enclosing_functions(call):
        qn = mod.qualname(fn)
        if qn in config.sync_allowlist or fn.name in config.sync_allowlist:
            return True
        if mod.node_marks(fn, "host-sync-ok"):
            return True
    return False


def check_ktpu002_sync(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    """In resident-surface modules, host-forcing calls on device-resident
    values are only legal at designated sync points: np.asarray on a
    sharded resident array caches `_npy_value` inside the jax Array and
    silently blocks the NEXT fold's donation (PR 4)."""
    if not config.is_surface_module(mod.relpath):
        return []
    out: List[Violation] = []
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        hit = _forcing_target(call)
        if hit is None:
            continue
        target, callee, always = hit
        devname = _device_like_subtree(mod, config, target)
        if devname is None and not always:
            continue
        if _sync_exempt(mod, config, call):
            continue
        out.append(
            Violation(
                rule="KTPU002",
                path=mod.relpath,
                line=call.lineno,
                scope=mod.qualname(call),
                detail=f"host-sync:{callee}({devname or '...'})",
                message=(
                    f"`{callee}` forces a device→host sync on "
                    f"`{devname or 'a device value'}` outside the sync-point "
                    "allowlist — on a resident/sharded array this caches "
                    "_npy_value and blocks later donation. Fetch via a "
                    "device-side copy at a declared sync point, or mark the "
                    "line `# ktpu: host-sync-ok <why>`."
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# KTPU003 — guarded-by
# ---------------------------------------------------------------------------

_CTOR_NAMES = {"__init__", "__post_init__"}


def _declared_attrs(
    mod: ModuleInfo, cls: ast.ClassDef, kind: str
) -> Dict[str, Tuple[str, int]]:
    """attr -> (normalized lock expr / confinement tag, declaring line)
    from `kind` annotations on class-body fields or `self.X = ...`
    assignments."""
    declared: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(cls):
        if mod.enclosing_class(node) is not cls and node is not cls:
            continue
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        marks = list(mod.marks(node.lineno, kind))
        # a standalone comment line above also declares (long assignments);
        # trailing comments of the PREVIOUS statement do not leak down
        if node.lineno > 1 and mod.lines[node.lineno - 2].lstrip().startswith("#"):
            marks += mod.marks(node.lineno - 1, kind)
        if not marks:
            continue
        arg = normalize_expr(marks[0].args[0]) if marks[0].args else "self._lock"
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Name):  # class-body field
                declared[tgt.id] = (arg, node.lineno)
            elif (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                declared[tgt.attr] = (arg, node.lineno)
    return declared


def _method_exempt(mod: ModuleInfo, fn, lock: str) -> bool:
    if fn.name in _CTOR_NAMES:
        return True
    if fn.name.endswith("_locked"):  # repo convention: caller holds the lock
        return True
    for mark in mod.node_marks(fn, "holds"):
        if not mark.args or any(normalize_expr(a) == lock for a in mark.args):
            return True
    return False


def _method_confined(mod: ModuleInfo, fn, tag: str) -> bool:
    if fn.name in _CTOR_NAMES:
        return True
    for mark in mod.node_marks(fn, "confined"):
        if not mark.args or any(normalize_expr(a) == tag for a in mark.args):
            return True
    return False


def check_ktpu003(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    out: List[Violation] = []
    for cls in ast.walk(mod.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        guarded = _declared_attrs(mod, cls, "guarded-by")
        confined = _declared_attrs(mod, cls, "confined")
        if not guarded and not confined:
            continue
        for node in ast.walk(cls):
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
                continue
            if mod.enclosing_class(node) is not cls:
                continue
            if node.attr in guarded:
                lock, decl_line = guarded[node.attr]
                if node.lineno == decl_line:  # the declaring assignment itself
                    continue
                fns = mod.enclosing_functions(node)
                if not fns:
                    continue
                if any(_method_exempt(mod, fn, lock) for fn in fns):
                    continue
                if lock in {normalize_expr(w) for w in mod.with_locks_around(node)}:
                    continue
                if mod.allowed(node, "KTPU003"):
                    continue
                out.append(
                    Violation(
                        rule="KTPU003",
                        path=mod.relpath,
                        line=node.lineno,
                        scope=mod.qualname(node),
                        detail=f"unguarded:{cls.name}.{node.attr}",
                        message=(
                            f"`self.{node.attr}` is declared "
                            f"`# ktpu: guarded-by({lock})` but is accessed here "
                            f"outside a `with {lock}:` block (and the method is "
                            "not marked `# ktpu: holds(...)` / `*_locked`). "
                            "Unlocked cross-thread access is how vocab-slot "
                            "interning silently corrupted label matching (PR 5)."
                        ),
                    )
                )
            elif node.attr in confined:
                tag, decl_line = confined[node.attr]
                if node.lineno == decl_line:
                    continue
                fns = mod.enclosing_functions(node)
                if not fns:
                    continue
                if any(_method_confined(mod, fn, tag) for fn in fns):
                    continue
                if mod.allowed(node, "KTPU003"):
                    continue
                out.append(
                    Violation(
                        rule="KTPU003",
                        path=mod.relpath,
                        line=node.lineno,
                        scope=mod.qualname(node),
                        detail=f"unconfined:{cls.name}.{node.attr}",
                        message=(
                            f"`self.{node.attr}` is declared "
                            f"`# ktpu: confined({tag})` — single-thread state "
                            "with NO lock — but this method does not carry "
                            f"the matching `# ktpu: confined({tag})` mark. "
                            "Either the access runs on another thread (a "
                            "race: add a real lock) or the method belongs to "
                            "the confined context (mark it)."
                        ),
                    )
                )
    return out


# ---------------------------------------------------------------------------
# KTPU004 — hot-path-host-sync
# ---------------------------------------------------------------------------

def check_ktpu004(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    """Inside functions marked `# ktpu: hot-path` (driver dispatch, the
    arbiter, the fold planners), NO device→host forcing is legal — a
    single hidden round-trip serializes the whole pipelined drain."""
    out: List[Violation] = []
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        hit = _forcing_target(call)
        if hit is None:
            continue
        target, callee, always = hit
        hot = None
        for fn in mod.enclosing_functions(call):
            if mod.node_marks(fn, "hot-path"):
                hot = fn
                break
        if hot is None:
            continue
        devname = _device_like_subtree(mod, config, target)
        if devname is None and not always:
            continue  # host→host asarray etc. is fine even on hot paths
        if mod.allowed(call, "KTPU004") or mod.marks(call.lineno, "host-sync-ok"):
            continue
        out.append(
            Violation(
                rule="KTPU004",
                path=mod.relpath,
                line=call.lineno,
                scope=mod.qualname(call),
                detail=f"hot-sync:{callee}({devname or '...'})",
                message=(
                    f"`{callee}` forces a device→host sync inside hot-path "
                    f"function `{hot.name}` — dispatch/arbiter/fold code "
                    "must stay free-running; fetch results at the batch's "
                    "designated fetch point instead."
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# KTPU005 — shadowed-module-import
# ---------------------------------------------------------------------------

def _module_level_names(mod: ModuleInfo) -> Set[str]:
    names: Set[str] = set()
    body = getattr(mod.tree, "body", [])
    for node in body:
        if isinstance(node, ast.Import):
            for a in node.names:
                names.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                names.add(a.asname or a.name)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    names.add(tgt.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            names.add(node.name)
    return names


def check_ktpu005(mod: ModuleInfo, config: AnalysisConfig) -> List[Violation]:
    """A function-local import that rebinds a module-level name makes the
    WHOLE function treat that name as local — any use before the import
    line raises UnboundLocalError at runtime (the seed `_bucket` bug,
    which broke warmup for every enable_preemption=False drain)."""
    module_names = _module_level_names(mod)
    out: List[Violation] = []
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        # local imports directly inside THIS function (not nested defs)
        local_imports: List[Tuple[str, int, ast.AST]] = []
        for node in ast.walk(fn):
            if mod.enclosing_function(node) is not fn:
                continue
            if isinstance(node, ast.Import):
                for a in node.names:
                    local_imports.append(
                        (a.asname or a.name.split(".")[0], node.lineno, node)
                    )
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    local_imports.append((a.asname or a.name, node.lineno, node))
        for name, line, node in local_imports:
            if name not in module_names:
                continue
            if mod.allowed(node, "KTPU005"):
                continue
            early_use = None
            for sub in ast.walk(fn):
                if (
                    isinstance(sub, ast.Name)
                    and sub.id == name
                    and isinstance(sub.ctx, ast.Load)
                    and sub.lineno < line
                    and mod.enclosing_function(sub) is fn
                ):
                    early_use = min(early_use or sub.lineno, sub.lineno)
            if early_use is not None:
                out.append(
                    Violation(
                        rule="KTPU005",
                        path=mod.relpath,
                        line=early_use,
                        scope=mod.qualname(fn) or fn.name,
                        detail=f"use-before-local-import:{name}",
                        message=(
                            f"`{name}` is read here but a local import at "
                            f"line {line} shadows the module-level binding, "
                            "making it function-local — this raises "
                            "UnboundLocalError at runtime (the seed "
                            "`_bucket` warmup breakage). Rename the local "
                            "import or move it above every use."
                        ),
                    )
                )
            else:
                out.append(
                    Violation(
                        rule="KTPU005",
                        path=mod.relpath,
                        line=line,
                        scope=mod.qualname(fn) or fn.name,
                        detail=f"shadowed-import:{name}",
                        message=(
                            f"local import rebinds module-level `{name}` — "
                            "every use in this function now resolves to the "
                            "local binding; a use added above this line "
                            "becomes an UnboundLocalError. Rename the local "
                            "alias (e.g. `as _{0}`) or drop the redundant "
                            "import.".format(name)
                        ),
                    )
                )
    return out


ALL_CHECKERS = (
    check_ktpu001,
    check_ktpu002_donation,
    check_ktpu002_sync,
    check_ktpu003,
    check_ktpu004,
    check_ktpu005,
)

# rule tags for per-rule wall-time attribution (core.run_checkers):
# both KTPU002 passes aggregate under the one rule id they report
for _chk, _rule in (
    (check_ktpu001, "KTPU001"),
    (check_ktpu002_donation, "KTPU002"),
    (check_ktpu002_sync, "KTPU002"),
    (check_ktpu003, "KTPU003"),
    (check_ktpu004, "KTPU004"),
    (check_ktpu005, "KTPU005"),
):
    _chk.rule = _rule


def repo_config() -> AnalysisConfig:
    """The tree's canonical policy: where jit construction is the module's
    job, which modules hold resident banks, and the designated sync
    points the resident-state plane documents."""
    return AnalysisConfig(
        jit_allowed_prefixes=(
            "kubernetes_tpu/compile/",
            "kubernetes_tpu/ops/",
            # the version-shim module whose whole purpose is wrapping
            # shard_map for jax 0.4.x/0.5.x — constructions inside it are
            # the factories' raw material, admitted at their call sites
            "kubernetes_tpu/parallel/mesh.py",
        ),
        surface_prefixes=(
            "kubernetes_tpu/state/cache.py",
            "kubernetes_tpu/ingest/",
            "kubernetes_tpu/terms_plane/",
            "kubernetes_tpu/commit/",
            "kubernetes_tpu/scheduler/driver.py",
            "kubernetes_tpu/parallel/sharded.py",
            # the flight recorder parks dispatched array handles for
            # two-phase device spans — its resolver is the ONLY place in
            # obs/ allowed to force, and only via the allowlist below
            "kubernetes_tpu/obs/",
        ),
        sync_allowlist=(
            # the mirror's parity probe fetches via a device-side copy —
            # THE designed sync point of the resident-state plane
            "TensorMirror.device_bank_divergence",
            # the batch's one designated solve-result fetch
            "Scheduler._finish_solve",
            # host-rank score rows bulk-fetch (Score plugins / extenders)
            "ScoreRows.prefetch",
            # the flight recorder's off-hot-path resolver of parked
            # two-phase device spans (export/drain time only; the hot
            # half, device_begin, never forces)
            "FlightRecorder.resolve_pending",
            # the staged banks' shadow-audit probe (fault-plane probe
            # gate): full-array fetch via a device-side copy, driver
            # thread, safe-sync-point only — the StageBank counterpart of
            # TensorMirror.device_bank_divergence (TermBankDevice
            # inherits it)
            "StageBank.device_divergence",
        ),
    )
