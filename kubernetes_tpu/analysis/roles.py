"""Thread-role inference + the interprocedural KTPU006–008 rules.

The scheduler runs ~10 concurrent thread roles (informer, the two bank
uploaders, driver, commit-apply worker, bind pool, health monitor,
compile-warmup worker, controller loops, serving muxes). Which role can
execute which function decides whether an attribute is shared, whether a
``confined(driver)`` claim is true, and whether a hot-path function can
transitively stumble into a host sync. This module computes that:

* **Seeds** — the ``# ktpu: thread-entry(<role>[, <role2>])`` grammar.
  On a ``def``, the function is an entry point executed by that role's
  thread (a thread target, a pool-submitted closure, an informer
  callback). On a spawn line (``threading.Thread(target=...)`` or
  ``pool.submit(...)``), the resolved target becomes the entry. A def
  may carry several roles (``StageBank._drain`` runs as either bank
  uploader depending on the subclass).
* **Propagation** — BFS over the repo call graph (callgraph.py): the
  role set of a function is every entry role that can reach it.
  Functions reachable from no entry have the empty role set — they run
  only on external callers (tests, __main__) and are exempt from the
  multi-role rules by construction.
* **KTPU006 shared-attribute inference** — a ``self.X`` attribute with
  accessor methods spanning ≥2 roles and ≥1 post-construction write
  must be declared ``guarded-by(...)`` or ``confined(...)`` (closing
  KTPU003's unannotated-attribute hole).
* **KTPU007 transitive hot-path sync** — no ``hot-path`` function may
  REACH a device→host forcing call through the graph, outside the sync
  allowlist (interprocedural KTPU004).
* **KTPU008 confinement reachability** — a ``confined(<role>)``-marked
  method reachable from any other role is a violation, and every thread
  spawn/submit site must be rooted in the role graph (an annotated line
  or an annotated resolved target) — unrooted spawns would silently
  blind all three rules.

The static inference is deliberately a superset (conservative dispatch,
fuzzy last-resort edges); its soundness probe is the runtime twin in
lockorder.py: threads register their role at spawn, audited locks record
which roles actually touched each lock role, and ``assert_roles_subset``
verifies observed ⊆ inferred (wired into the lock-audited perf_smoke
drains — a run where reality escapes the inference fails the build).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import (
    ClassInfo,
    FuncInfo,
    RepoGraph,
    load_graph,
)
from .checkers import (
    _declared_attrs,
    _device_like_subtree,
    _forcing_target,
)
from .core import AnalysisConfig, ModuleInfo, Violation, dotted_name

#: lock roles every thread may touch by design: the metrics registry and
#: its per-metric locks are process-global leaf primitives (kube's
#: prometheus client has the same shape), the event recorder is a
#: fire-and-forget sink, and the breaker board's own lock is — per its
#: documented contract — "callable from any thread (may hold a plane
#: lock — the board lock is a leaf)": every plane thread reports its own
#: faults. Declaring these role-universal keeps the runtime subset
#: assertion honest instead of vacuously failing on by-design
#: omnidirectional leaf locks; every OTHER lock role must be reached by
#: the static inference for the roles that really touch it.
OMNI_LOCK_ROLES = frozenset({
    "metric", "metrics-registry", "event-recorder", "faults",
})

#: escape hatch for lock roles reached only through indirection the call
#: graph cannot see (each entry documents WHY). Additions are reviewed
#: knowledge, not a dumping ground — the runtime audit fails loudly when
#: an entry is missing, and an entry here is a TODO for better
#: resolution, not a license to stop resolving.
EXTRA_STATIC_ROLES: Dict[str, Set[str]] = {
    # APIBinder.bind is reached from bind workers through the Binder's
    # stored callback (`Binder(api_binder.bind)` — a function attribute
    # the graph cannot type), and from there the apiserver store/persist
    # locks; the informer's relist reaches them resolvably, the bind
    # side does not.
    "apiserver-store": {"bind", "driver"},
    "apiserver-persist": {"bind", "driver"},
    "apiserver-auth": {"bind", "driver"},
    # enqueue-time encoding: PriorityQueue.add stages pod/term rows ON
    # THE ADMITTING THREAD (the informer) through the plane-tuple
    # indirection (_planes_locked yields (stage, row_attr, gen_attr)
    # tuples), which erases the receiver type the graph would need to
    # resolve `stage.acquire(...)`.
    "stage": {"informer"},
    # ... and the terms lock is ADDITIONALLY touched by the terms
    # uploader: TermBankDevice inherits StageBank.__init__ whose
    # `stage: PodStage` annotation cannot express the duck-typed
    # TermStage it actually receives, so the `self._lock = stage._lock`
    # alias resolves to the "stage" role only. (Caught live by
    # assert_roles_subset the first time the probe ran — the soundness
    # loop doing its job.)
    "terms": {"informer", "terms-upload"},
    "vocab-slots": {"informer"},
    # plugin dispatch: Framework.run_permit/pre_bind/bind run REGISTERED
    # plugin objects against the CycleState on the bind workers; the
    # plugin list is runtime data the graph cannot enumerate.
    "cycle-state": {"bind"},
}

#: attribute values that are themselves synchronization/thread-safe
#: primitives — assigning one in the ctor exempts the attribute from
#: KTPU006 (the primitive IS the discipline)
_THREADSAFE_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "ThreadPoolExecutor",
    "local", "audited_lock", "audited_rlock", "audited_condition",
})

_CTOR_NAMES = {"__init__", "__post_init__"}


# ---------------------------------------------------------------------------
# entry collection + propagation
# ---------------------------------------------------------------------------

def _spawn_sites(graph: RepoGraph) -> List[Tuple[FuncInfo, ast.Call, str]]:
    """(enclosing function, call, kind) for every thread spawn or pool
    submit in the graph's modules. kind: "thread" | "submit"."""
    out: List[Tuple[FuncInfo, ast.Call, str]] = []
    for fi in graph.functions.values():
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            owner = graph.function_for_node(fi.mod, node)
            if owner is None or owner.uid != fi.uid:
                continue
            nm = dotted_name(node.func) or ""
            last = nm.split(".")[-1]
            if last == "Thread" and any(k.arg == "target" for k in node.keywords):
                out.append((fi, node, "thread"))
            elif isinstance(node.func, ast.Attribute) and node.func.attr == "submit":
                out.append((fi, node, "submit"))
    return out


def _spawn_target_expr(call: ast.Call, kind: str) -> Optional[ast.AST]:
    if kind == "thread":
        for k in call.keywords:
            if k.arg == "target":
                return k.value
        return None
    return call.args[0] if call.args else None


def _resolve_callable_ref(
    graph: RepoGraph, fi: FuncInfo, expr: ast.AST
) -> List[FuncInfo]:
    """A callable REFERENCE (not a call): self._drain, a nested def's
    name, a module function, an imported symbol."""
    if expr is None:
        return []
    if isinstance(expr, ast.Attribute):
        if isinstance(expr.value, ast.Name) and expr.value.id == "self" and fi.owner_cls:
            return fi.owner_cls.find_method(expr.attr)
        nm = dotted_name(expr.value)
        if nm is not None:
            tgt = graph.imports.get(fi.relpath, {}).get(nm.split(".")[0])
            if tgt is not None and tgt[0] == "module":
                mfi = graph.module_funcs.get((tgt[1], expr.attr))
                return [mfi] if mfi else []
        return []
    if isinstance(expr, ast.Name):
        for encl in [fi.node] + fi.mod.enclosing_functions(fi.node):
            for sub in ast.walk(encl):
                if (
                    isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and sub.name == expr.id
                ):
                    uid = graph.node_uid.get(id(sub))
                    if uid:
                        return [graph.functions[uid]]
        mfi = graph.module_funcs.get((fi.relpath, expr.id))
        if mfi is not None:
            return [mfi]
        tgt = graph.imports.get(fi.relpath, {}).get(expr.id)
        if tgt is not None and tgt[0] == "symbol":
            mfi = graph.module_funcs.get((tgt[1], tgt[2]))
            return [mfi] if mfi else []
    return []


def _line_marks(mod: ModuleInfo, line: int, kind: str):
    """Markers on `line` or the contiguous comment block above it (the
    one shared definition: ModuleInfo.comment_block_lines)."""
    out = []
    for ln in mod.comment_block_lines(line):
        out += mod.marks(ln, kind)
    return out


def collect_entries(graph: RepoGraph) -> Dict[str, Set[str]]:
    """uid -> declared role set, from thread-entry def marks and
    annotated spawn/submit lines."""
    entries: Dict[str, Set[str]] = {}
    for fi in graph.functions.values():
        for mark in fi.mod.node_marks(fi.node, "thread-entry"):
            entries.setdefault(fi.uid, set()).update(mark.args or ("unnamed",))
    for fi, call, kind in _spawn_sites(graph):
        marks = _line_marks(fi.mod, call.lineno, "thread-entry")
        if not marks:
            continue
        roles: Set[str] = set()
        for m in marks:
            roles.update(m.args or ("unnamed",))
        for target in _resolve_callable_ref(
            graph, fi, _spawn_target_expr(call, kind)
        ):
            entries.setdefault(target.uid, set()).update(roles)
    return entries


def propagate_roles(
    graph: RepoGraph, entries: Dict[str, Set[str]], fuzzy: bool = True
) -> Dict[str, Set[str]]:
    """Role set per function uid: every entry role that can reach it."""
    roles: Dict[str, Set[str]] = {uid: set(rs) for uid, rs in entries.items()}
    frontier = list(entries)
    while frontier:
        uid = frontier.pop()
        src_roles = roles.get(uid, set())
        for edge in graph.callees(uid, fuzzy=fuzzy):
            dst = roles.setdefault(edge.dst, set())
            if not src_roles <= dst:
                dst.update(src_roles)
                frontier.append(edge.dst)
    return roles


class RoleAnalysis:
    """One pass over a graph: entries, propagated roles, and the
    shared config — the object the KTPU006–008 checkers consume."""

    def __init__(self, graph: RepoGraph, config: AnalysisConfig):
        self.graph = graph
        self.config = config
        self.entries = collect_entries(graph)
        self.roles = propagate_roles(graph, self.entries)

    def roles_of(self, uid: str) -> Set[str]:
        return self.roles.get(uid, set())


# ---------------------------------------------------------------------------
# KTPU006 — shared-attribute inference
# ---------------------------------------------------------------------------

def _ctor_threadsafe_attrs(ci: ClassInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(ci.node):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not isinstance(v, ast.Call):
            continue
        if (dotted_name(v.func) or "").split(".")[-1] not in _THREADSAFE_CTORS:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                out.add(tgt.attr)
    return out


def check_ktpu006(analysis: RoleAnalysis) -> List[Violation]:
    graph, out = analysis.graph, []
    for ci in graph.classes.values():
        mod = ci.mod
        # declarations/exemptions union over the CLASS HIERARCHY: a
        # subclass method touching an attr its base declared guarded-by
        # (the StageBank/TermBankDevice shape) must see the declaration
        declared: Set[str] = set()
        exempt: Set[str] = set()
        # attrs holding dict literals: for these, element stores
        # (self.stats["k"] += 1 — the classic lost-update counter) count
        # as writes. Array-buffer attrs (np.zeros row slabs) are excluded:
        # their row writes are the planes' externally-locked scatter
        # idiom, and flagging every encoder bank row would drown the rule
        dict_attrs: Set[str] = set()
        # an `# ktpu: allow(KTPU006) <why>` on an attribute's ASSIGNMENT
        # exempts the whole attribute — the honest annotation for
        # externally-synchronized value objects (NodeInfo under the cache
        # lock), idempotent memos, and driver→worker handoff objects
        allow_attrs: Set[str] = set()
        for anc in ci.mro_like():
            declared.update(_declared_attrs(anc.mod, anc.node, "guarded-by"))
            declared.update(_declared_attrs(anc.mod, anc.node, "confined"))
            exempt |= _ctor_threadsafe_attrs(anc) | set(anc.lock_attrs)
            for n in ast.walk(anc.node):
                if isinstance(n, ast.Assign):
                    tgts = n.targets
                elif isinstance(n, ast.AnnAssign):
                    tgts = [n.target]
                else:
                    continue
                targets = [
                    t.attr
                    for t in tgts
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ]
                if not targets:
                    continue
                if isinstance(n.value, ast.Dict):
                    dict_attrs.update(targets)
                if anc.mod.allowed(n, "KTPU006"):
                    allow_attrs.update(targets)
        exempt |= allow_attrs
        # attr -> (roles union, non-ctor write line, accessors sample)
        attr_roles: Dict[str, Set[str]] = {}
        attr_write: Dict[str, int] = {}
        attr_fns: Dict[str, Set[str]] = {}
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Attribute):
                continue
            if not (isinstance(node.value, ast.Name) and node.value.id == "self"):
                continue
            if mod.enclosing_class(node) is not ci.node:
                continue  # nested class: its own ClassInfo owns the access
            fi = graph.function_for_node(mod, node)
            if fi is None:
                continue
            roles = analysis.roles_of(fi.uid)
            if mod.allowed(node, "KTPU006"):
                continue
            in_ctor = any(
                f.name in _CTOR_NAMES
                for f in [fi.node] + mod.enclosing_functions(fi.node)
                if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            )
            if in_ctor:
                continue  # construction-time publication precedes spawn
            if roles:
                attr_roles.setdefault(node.attr, set()).update(roles)
                attr_fns.setdefault(node.attr, set()).add(fi.qualname)
            # a write is a rebind (self.X = ...) OR an element store
            # through the attribute (self.X[k] = ... / += ...): the dict-
            # counter idiom is exactly the cross-thread lost-update shape
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if not is_write and node.attr in dict_attrs:
                parent = mod.parents.get(node)
                if (
                    isinstance(parent, ast.Subscript)
                    and parent.value is node
                    and isinstance(parent.ctx, (ast.Store, ast.Del))
                ):
                    is_write = True
            if is_write and roles:
                attr_write.setdefault(node.attr, node.lineno)
        for attr, roles in sorted(attr_roles.items()):
            if len(roles) < 2 or attr in declared or attr in exempt:
                continue
            line = attr_write.get(attr)
            if line is None:
                continue  # read-only outside the ctor: safe publication
            out.append(
                Violation(
                    rule="KTPU006",
                    path=ci.relpath,
                    line=line,
                    scope=ci.name,
                    detail=f"shared:{ci.name}.{attr}",
                    message=(
                        f"`self.{attr}` is written post-construction and is "
                        f"reachable from {len(roles)} thread roles "
                        f"({', '.join(sorted(roles))}; accessors: "
                        f"{', '.join(sorted(attr_fns.get(attr, ()))[:4])}) "
                        "but carries no `# ktpu: guarded-by(...)` or "
                        "`confined(...)` declaration — the unannotated "
                        "cross-thread attribute KTPU003 cannot see. Declare "
                        "the discipline (and satisfy KTPU003), or confine "
                        "the writes to one role."
                    ),
                )
            )
    return out


# ---------------------------------------------------------------------------
# KTPU007 — transitive hot-path sync
# ---------------------------------------------------------------------------

def _fn_is_barrier(fi: FuncInfo, config: AnalysisConfig) -> bool:
    """Designated sync points end traversal: their forcing is the
    designed fetch, and everything under them runs at that sync."""
    qn = fi.qualname
    if qn in config.sync_allowlist or fi.name in config.sync_allowlist:
        return True
    if fi.mod.node_marks(fi.node, "host-sync-ok"):
        return True
    return False


def _fn_forcings(
    fi: FuncInfo, config: AnalysisConfig
) -> List[Tuple[str, str, int]]:
    """(callee, devname, line) for unexempted forcing calls owned by fi."""
    mod, out = fi.mod, []
    for node in ast.walk(fi.node):
        if not isinstance(node, ast.Call):
            continue
        if mod.enclosing_function(node) is not fi.node:
            continue
        hit = _forcing_target(node)
        if hit is None:
            continue
        target, callee, always = hit
        devname = _device_like_subtree(mod, config, target)
        if devname is None and not always:
            continue
        if (
            mod.allowed(node, "KTPU007")
            or mod.allowed(node, "KTPU004")
            or mod.marks(node.lineno, "host-sync-ok")
        ):
            continue
        out.append((callee, devname or "...", node.lineno))
    return out


def check_ktpu007(analysis: RoleAnalysis) -> List[Violation]:
    graph, config = analysis.graph, analysis.config
    forcings = {
        uid: _fn_forcings(fi, config) for uid, fi in graph.functions.items()
    }
    out: List[Violation] = []
    for uid, fi in graph.functions.items():
        if not fi.mod.node_marks(fi.node, "hot-path"):
            continue
        if fi.mod.allowed(fi.node, "KTPU007"):
            continue
        # BFS with parents for the reported chain; barriers not entered
        parent: Dict[str, str] = {uid: ""}
        frontier = [uid]
        reported: Set[str] = set()
        while frontier:
            cur = frontier.pop(0)
            for edge in graph.callees(cur):
                dst = edge.dst
                if dst in parent:
                    continue
                dfi = graph.functions.get(dst)
                if dfi is None:
                    continue
                if _fn_is_barrier(dfi, config):
                    continue
                parent[dst] = cur
                frontier.append(dst)
                if forcings.get(dst) and dst not in reported:
                    reported.add(dst)
                    chain: List[str] = []
                    walk = dst
                    while walk:
                        chain.append(graph.functions[walk].qualname)
                        walk = parent[walk]
                    callee, devname, fline = forcings[dst][0]
                    out.append(
                        Violation(
                            rule="KTPU007",
                            path=fi.relpath,
                            line=fi.node.lineno,
                            scope=fi.qualname,
                            detail=f"hot-reach:{fi.qualname}->{dfi.qualname}",
                            message=(
                                f"hot-path `{fi.qualname}` reaches a device→"
                                f"host forcing call `{callee}({devname})` at "
                                f"{dfi.relpath}:{fline} through "
                                f"{' -> '.join(reversed(chain))} — the "
                                "transitive twin of KTPU004: one hidden sync "
                                "one call deep serializes the whole drain. "
                                "Route the fetch through a declared sync "
                                "point (sync_allowlist / host-sync-ok) or "
                                "break the call chain."
                            ),
                        )
                    )
    return out


# ---------------------------------------------------------------------------
# KTPU008 — confinement reachability + rooted spawns
# ---------------------------------------------------------------------------

def check_ktpu008(analysis: RoleAnalysis) -> List[Violation]:
    graph = analysis.graph
    out: List[Violation] = []
    for uid, fi in graph.functions.items():
        marks = fi.mod.node_marks(fi.node, "confined")
        if not marks:
            continue
        if fi.mod.allowed(fi.node, "KTPU008"):
            continue
        tags: Set[str] = set()
        for m in marks:
            tags.update(m.args)
        if not tags:
            continue
        foreign = analysis.roles_of(uid) - tags
        if foreign:
            out.append(
                Violation(
                    rule="KTPU008",
                    path=fi.relpath,
                    line=fi.node.lineno,
                    scope=fi.qualname,
                    detail=f"confined-reach:{fi.qualname}",
                    message=(
                        f"`{fi.qualname}` is declared `# ktpu: confined("
                        f"{','.join(sorted(tags))})` — lock-FREE single-"
                        "thread state — but the role graph shows it "
                        f"reachable from {', '.join(sorted(foreign))}. "
                        "Either the reaching path is real (a race: add a "
                        "lock or publish via a mailbox) or the confinement "
                        "tag/role seeds are wrong — fix whichever is lying."
                    ),
                )
            )
    # rooted-spawn contract: every spawn/submit site must seed the role
    # graph (an annotated line, or a resolved target whose def is
    # annotated) — an unrooted spawn blinds KTPU006/007/008 silently
    for fi, call, kind in _spawn_sites(graph):
        if _line_marks(fi.mod, call.lineno, "thread-entry"):
            continue
        if fi.mod.allowed(call, "KTPU008"):
            continue
        targets = _resolve_callable_ref(
            graph, fi, _spawn_target_expr(call, kind)
        )
        if targets and all(
            t.mod.node_marks(t.node, "thread-entry") for t in targets
        ):
            continue
        tgt_repr = ""
        expr = _spawn_target_expr(call, kind)
        if expr is not None:
            try:
                tgt_repr = ast.unparse(expr)
            except Exception:
                tgt_repr = "?"
        out.append(
            Violation(
                rule="KTPU008",
                path=fi.relpath,
                line=call.lineno,
                scope=fi.qualname,
                detail=f"unrooted-spawn:{tgt_repr}",
                message=(
                    f"thread {'spawn' if kind == 'thread' else 'submit'} of "
                    f"`{tgt_repr}` is not rooted in the role graph: mark "
                    "the line (or the target def) `# ktpu: thread-entry("
                    "<role>)` so role inference can see the code this "
                    "thread executes — unannotated spawns silently blind "
                    "KTPU006/007/008."
                ),
            )
        )
    return out


# ---------------------------------------------------------------------------
# running the repo-wide rules
# ---------------------------------------------------------------------------

REPO_RULES = ("KTPU006", "KTPU007", "KTPU008")

_REPO_CHECKERS = {
    "KTPU006": check_ktpu006,
    "KTPU007": check_ktpu007,
    "KTPU008": check_ktpu008,
}


def run_repo_checkers(
    graph: RepoGraph,
    config: AnalysisConfig,
    rules: Optional[Set[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    import time as _time

    analysis = RoleAnalysis(graph, config)
    out: List[Violation] = []
    for rule, chk in _REPO_CHECKERS.items():
        if rules and rule not in rules:
            continue
        t0 = _time.perf_counter()
        out.extend(chk(analysis))
        if timings is not None:
            timings[rule] = timings.get(rule, 0.0) + _time.perf_counter() - t0
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def scan_repo_rules(
    paths: Sequence[str],
    repo_root: str,
    config: AnalysisConfig,
    rules: Optional[Set[str]] = None,
    timings: Optional[Dict[str, float]] = None,
) -> List[Violation]:
    graph = load_graph(paths, repo_root)
    return run_repo_checkers(graph, config, rules, timings)


# ---------------------------------------------------------------------------
# static lock-role inference (the runtime twin's reference map)
# ---------------------------------------------------------------------------

def static_lock_roles(analysis: RoleAnalysis) -> Dict[str, Set[str]]:
    """lock role -> set of thread roles statically able to touch it.

    Conservative by construction: a lock constructed by class C is
    credited with every role that reaches ANY method of C or its repo
    subclasses (any method might acquire). OMNI_LOCK_ROLES map to the
    universal set ("*"); EXTRA_STATIC_ROLES patches the documented
    callback-indirection gaps."""
    graph = analysis.graph
    out: Dict[str, Set[str]] = {name: {"*"} for name in OMNI_LOCK_ROLES}
    for ci in graph.classes.values():
        lock_roles: Set[str] = set()
        for rs in ci.lock_attrs.values():
            lock_roles |= rs
        if not lock_roles:
            continue
        method_roles: Set[str] = set()
        seen: Set[Tuple[str, str]] = set()
        frontier = [ci]
        while frontier:
            c = frontier.pop()
            if c.key in seen:
                continue
            seen.add(c.key)
            for m in c.methods.values():
                method_roles |= analysis.roles_of(m.uid)
            frontier.extend(c.subclasses)
            frontier.extend(c.bases)  # inherited methods run as self=C
        for name in lock_roles:
            out.setdefault(name, set()).update(method_roles)
    for name, extra in EXTRA_STATIC_ROLES.items():
        out.setdefault(name, set()).update(extra)
    return out


_RUNTIME_STATIC_CACHE: Dict[str, Dict[str, Set[str]]] = {}


def runtime_static_roles(
    config: Optional[AnalysisConfig] = None,
) -> Dict[str, Set[str]]:
    """The installed package's static lock-role map — what the runtime
    audit's observed roles must be a subset of. Memoized per package dir
    (the source tree does not change mid-process; three audited smoke
    tests in one pytest run should pay the graph build once)."""
    from .checkers import repo_config

    import kubernetes_tpu

    pkg_dir = os.path.dirname(os.path.abspath(kubernetes_tpu.__file__))
    # memoize ONLY the default-config map: an id(config)-keyed entry
    # could silently alias a later config object allocated at a freed
    # address, returning the wrong static map to the soundness probe
    if config is None:
        cached = _RUNTIME_STATIC_CACHE.get(pkg_dir)
        if cached is not None:
            return cached
    repo_root = os.path.dirname(pkg_dir)
    graph = load_graph([pkg_dir], repo_root)
    analysis = RoleAnalysis(graph, config or repo_config())
    out = static_lock_roles(analysis)
    if config is None:
        _RUNTIME_STATIC_CACHE[pkg_dir] = out
    return out


def assert_runtime_subset(registry=None) -> Dict[str, object]:
    """The perf_smoke soundness probe: observed lock-touching roles must
    be contained in the static inference, and the observed graph must be
    non-empty (silent unwiring of the role registrations fails exactly
    like the lock-audit's non-empty-edge assertion). Returns a report
    dict for the caller's detail output."""
    if registry is None:
        from .lockorder import REGISTRY as registry  # noqa: N813
    static = runtime_static_roles()
    registry.assert_roles_subset(static)
    return {
        "observed": {k: sorted(v) for k, v in registry.observed_roles().items()},
        "static_locks": len(static),
    }
