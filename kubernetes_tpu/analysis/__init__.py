"""Static invariant analysis + runtime lock-order auditing.

The repo's three hard-won invariant families — planned XLA compiles,
donation-safe device residency, and lock-guarded shared state — are
enforced here as machine-checked rules instead of review lore:

  scripts/ktpu_lint.py        CLI over the checker registry (``--check``
                              gates preflight and tier-1)
  analysis/core.py            walk/annotation/baseline infrastructure
  analysis/checkers.py        the module-local KTPU001..KTPU005 rules
  analysis/callgraph.py       repo-wide conservative call graph
  analysis/roles.py           thread-role inference + the
                              interprocedural KTPU006..KTPU008 rules
  analysis/lockorder.py       runtime lock-order/race harness + the
                              thread-role audit twin (KTPU_LOCK_AUDIT=1)

Each rule is the static twin of a runtime guarantee the benches already
assert (see INVARIANTS.md for the rule → historical-bug cross-reference).
"""

from .core import (  # noqa: F401
    AnalysisConfig,
    Baseline,
    ModuleInfo,
    Violation,
    iter_python_files,
    load_module,
    run_checkers,
    scan_paths,
)
from .checkers import ALL_CHECKERS, repo_config  # noqa: F401
