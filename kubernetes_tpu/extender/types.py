"""extender/v1 wire types.

Faithful JSON shapes of pkg/scheduler/apis/extender/v1/types.go (mirrored
in staging/src/k8s.io/kube-scheduler/extender/v1): the Go structs carry no
json tags, so the wire keys are the exported field names verbatim ("Pod",
"NodeNames", "FailedNodes", ...). Pods/Nodes embed full v1 objects and are
converted through api.types.{pod,node}_{from,to}_k8s.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api.types import Node, Pod, node_from_k8s, node_to_k8s, pod_from_k8s, pod_to_k8s

MIN_EXTENDER_PRIORITY = 0
MAX_EXTENDER_PRIORITY = 10


@dataclass
class ExtenderArgs:
    pod: Optional[Pod] = None
    nodes: Optional[List[Node]] = None  # NodeCacheCapable == false
    node_names: Optional[List[str]] = None  # NodeCacheCapable == true

    @staticmethod
    def from_json(d: dict) -> "ExtenderArgs":
        nodes = None
        if d.get("Nodes") is not None:
            nodes = [node_from_k8s(o) for o in d["Nodes"].get("items") or []]
        return ExtenderArgs(
            pod=pod_from_k8s(d["Pod"]) if d.get("Pod") is not None else None,
            nodes=nodes,
            node_names=list(d["NodeNames"]) if d.get("NodeNames") is not None else None,
        )

    def to_json(self) -> dict:
        return {
            "Pod": pod_to_k8s(self.pod) if self.pod is not None else None,
            "Nodes": (
                {"items": [node_to_k8s(n) for n in self.nodes]} if self.nodes is not None else None
            ),
            "NodeNames": self.node_names,
        }


@dataclass
class ExtenderFilterResult:
    nodes: Optional[List[Node]] = None
    node_names: Optional[List[str]] = None
    failed_nodes: Dict[str, str] = field(default_factory=dict)
    error: str = ""

    @staticmethod
    def from_json(d: dict) -> "ExtenderFilterResult":
        nodes = None
        if d.get("Nodes") is not None:
            nodes = [node_from_k8s(o) for o in d["Nodes"].get("items") or []]
        return ExtenderFilterResult(
            nodes=nodes,
            node_names=list(d["NodeNames"]) if d.get("NodeNames") is not None else None,
            failed_nodes=dict(d.get("FailedNodes") or {}),
            error=d.get("Error", "") or "",
        )

    def to_json(self) -> dict:
        return {
            "Nodes": (
                {"items": [node_to_k8s(n) for n in self.nodes]} if self.nodes is not None else None
            ),
            "NodeNames": self.node_names,
            "FailedNodes": self.failed_nodes,
            "Error": self.error,
        }


@dataclass
class HostPriority:
    host: str = ""
    score: int = 0

    @staticmethod
    def from_json(d: dict) -> "HostPriority":
        return HostPriority(host=d.get("Host", ""), score=int(d.get("Score", 0)))

    def to_json(self) -> dict:
        return {"Host": self.host, "Score": self.score}


@dataclass
class ExtenderBindingArgs:
    pod_name: str = ""
    pod_namespace: str = ""
    pod_uid: str = ""
    node: str = ""

    @staticmethod
    def from_json(d: dict) -> "ExtenderBindingArgs":
        return ExtenderBindingArgs(
            pod_name=d.get("PodName", ""),
            pod_namespace=d.get("PodNamespace", ""),
            pod_uid=str(d.get("PodUID", "")),
            node=d.get("Node", ""),
        )

    def to_json(self) -> dict:
        return {
            "PodName": self.pod_name,
            "PodNamespace": self.pod_namespace,
            "PodUID": self.pod_uid,
            "Node": self.node,
        }


@dataclass
class ExtenderBindingResult:
    error: str = ""

    @staticmethod
    def from_json(d: dict) -> "ExtenderBindingResult":
        return ExtenderBindingResult(error=d.get("Error", "") or "")

    def to_json(self) -> dict:
        return {"Error": self.error}


@dataclass
class Victims:
    pods: List[Pod] = field(default_factory=list)
    num_pdb_violations: int = 0

    @staticmethod
    def from_json(d: dict) -> "Victims":
        return Victims(
            pods=[pod_from_k8s(p) for p in d.get("Pods") or []],
            num_pdb_violations=int(d.get("NumPDBViolations", 0)),
        )

    def to_json(self) -> dict:
        return {
            "Pods": [pod_to_k8s(p) for p in self.pods],
            "NumPDBViolations": self.num_pdb_violations,
        }


@dataclass
class MetaVictims:
    pod_uids: List[str] = field(default_factory=list)
    num_pdb_violations: int = 0

    @staticmethod
    def from_json(d: dict) -> "MetaVictims":
        return MetaVictims(
            pod_uids=[p.get("UID", "") for p in d.get("Pods") or []],
            num_pdb_violations=int(d.get("NumPDBViolations", 0)),
        )

    def to_json(self) -> dict:
        return {
            "Pods": [{"UID": u} for u in self.pod_uids],
            "NumPDBViolations": self.num_pdb_violations,
        }


@dataclass
class ExtenderPreemptionArgs:
    pod: Optional[Pod] = None
    node_name_to_victims: Dict[str, Victims] = field(default_factory=dict)
    node_name_to_meta_victims: Dict[str, MetaVictims] = field(default_factory=dict)

    @staticmethod
    def from_json(d: dict) -> "ExtenderPreemptionArgs":
        return ExtenderPreemptionArgs(
            pod=pod_from_k8s(d["Pod"]) if d.get("Pod") is not None else None,
            node_name_to_victims={
                k: Victims.from_json(v) for k, v in (d.get("NodeNameToVictims") or {}).items()
            },
            node_name_to_meta_victims={
                k: MetaVictims.from_json(v)
                for k, v in (d.get("NodeNameToMetaVictims") or {}).items()
            },
        )

    def to_json(self) -> dict:
        out: dict = {"Pod": pod_to_k8s(self.pod) if self.pod is not None else None}
        if self.node_name_to_victims:
            out["NodeNameToVictims"] = {
                k: v.to_json() for k, v in self.node_name_to_victims.items()
            }
        if self.node_name_to_meta_victims:
            out["NodeNameToMetaVictims"] = {
                k: v.to_json() for k, v in self.node_name_to_meta_victims.items()
            }
        return out


@dataclass
class ExtenderPreemptionResult:
    node_name_to_meta_victims: Dict[str, MetaVictims] = field(default_factory=dict)

    @staticmethod
    def from_json(d: dict) -> "ExtenderPreemptionResult":
        return ExtenderPreemptionResult(
            node_name_to_meta_victims={
                k: MetaVictims.from_json(v)
                for k, v in (d.get("NodeNameToMetaVictims") or {}).items()
            }
        )

    def to_json(self) -> dict:
        return {
            "NodeNameToMetaVictims": {
                k: v.to_json() for k, v in self.node_name_to_meta_victims.items()
            }
        }
