"""HTTP SchedulerExtender server — the north-star integration seam.

A real kube-scheduler configured with

    {"urlPrefix": "http://host:port", "filterVerb": "filter",
     "prioritizeVerb": "prioritize", "bindVerb": "bind",
     "preemptVerb": "preemption", "nodeCacheCapable": true, "weight": 1}

POSTs extender/v1 JSON here per scheduling cycle
(core/extender.go:43 HTTPExtender.send → :305-331 nodeCacheCapable wire
modes) and this server answers from the TPU solver's state:

* /filter — feasibility for one pod over the candidate set. In
  nodeCacheCapable mode only node NAMES cross the wire and candidates
  resolve against this server's own cluster cache; otherwise full
  v1.Node objects arrive and are evaluated as a transient snapshot.
  Large candidate sets route through the device mask kernels (one fused
  [1, N] filter dispatch on the mirror); small ones use the scalar oracle.
* /prioritize — 0..10 host priorities (MaxExtenderPriority) from the
  default weighted score set.
* /bind — delegated binding (factory.go:713 equivalent) via bind_fn.
* /preemption — victim-map validation; answers in MetaVictims (UID-only)
  form when the args came nodeCacheCapable.

The server is the deployment story from BASELINE: front an unmodified
kube-scheduler with the batch solver without forking it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_lock
from ..api.types import Node, Pod
from ..oracle import Snapshot
from ..oracle.predicates import compute_predicate_metadata, pod_fits_on_node
from ..oracle.priorities import prioritize_nodes
from ..state.cache import SchedulerCache, TensorMirror
from .types import (
    MAX_EXTENDER_PRIORITY,
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderBindingResult,
    ExtenderFilterResult,
    ExtenderPreemptionArgs,
    ExtenderPreemptionResult,
    HostPriority,
    MetaVictims,
)


class ExtenderServer:
    """The solver-backed extender. Feed its cache from an informer (or the
    fake apiserver); start() serves on a daemon thread."""

    def __init__(
        self,
        cache: Optional[SchedulerCache] = None,
        bind_fn: Optional[Callable[[ExtenderBindingArgs], None]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        device_threshold: int = 256,
        enabled_predicates: Optional[frozenset] = None,
        priority_weights=None,  # tuple of (registration name, weight)
        rtcr=None,  # RequestedToCapacityRatio (shape, resources) Policy args
    ):
        self.cache = cache or SchedulerCache()
        self.bind_fn = bind_fn
        self.device_threshold = device_threshold
        # Policy/provider selection (config.factory): gates the oracle
        # chain, the device mask, and the prioritize weights
        self.enabled_predicates = enabled_predicates
        self.priority_weights = tuple(priority_weights) if priority_weights else None
        self.rtcr = rtcr
        self._mirror: Optional[TensorMirror] = None
        self._mirror_lock = audited_lock("extender-mirror")
        # per-pod-spec encode memo for /filter: repeated requests for
        # same-spec pods (every replica of a controller, the common
        # extender traffic) reuse one PodBatch row + compiled TermBank
        # instead of re-encoding per HTTP request — the term plane's
        # interning idea at this seam. Keyed by spec_key; entries are
        # immutable host arrays; invalidated wholesale when the vocab's
        # encoding widths grow (the arrays would be the wrong shape).
        self._enc_cache: Dict = {}  # ktpu: guarded-by(self._mirror_lock)
        self._enc_cache_widths = None  # ktpu: guarded-by(self._mirror_lock)
        self.filter_encode_cache = {"hits": 0, "misses": 0}  # ktpu: guarded-by(self._mirror_lock)
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        h, p = self.address
        return f"http://{h}:{p}"

    def start(self) -> "ExtenderServer":
        # ktpu: thread-entry(extender-serve) stdlib mux: handlers run on
        # socketserver threads the call graph cannot follow
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # -- core answers --------------------------------------------------------

    def _device_filter(self, pod: Pod, names: List[str]) -> Optional[Dict[str, bool]]:
        """One fused [1, N] mask dispatch over the cache mirror; None when
        the encoding can't represent the pod/nodes (caller falls back to the
        oracle)."""
        try:
            import jax.numpy as jnp
            import numpy as np

            from ..ops import filters as F
            from ..ops.pipeline import SolveConfig, filter_mask
            from ..state.tensors import PodBatch, _bucket, spec_key
            from ..state.terms import compile_batch_terms

            with self._mirror_lock:
                if self._mirror is None:
                    self._mirror = TensorMirror(self.cache)
                mirror = self._mirror
                mirror.sync()
                # any node row in encoding fallback → the device mask can't
                # answer for the whole set; bail before paying the encode +
                # dispatch cost
                if bool((mirror.nodes.fallback & mirror.nodes.valid).any()):
                    return None
                widths = (
                    mirror.vocab.config.key_slots,
                    mirror.vocab.config.resource_slots,
                )
                if widths != self._enc_cache_widths:
                    # a vocab width growth makes every cached array the
                    # wrong shape — drop the memo wholesale
                    self._enc_cache.clear()
                    self._enc_cache_widths = widths
                key = spec_key(pod)
                cached = self._enc_cache.get(key)
                if cached is None:
                    self.filter_encode_cache["misses"] += 1
                    batch = PodBatch(mirror.vocab, _bucket(1))
                    batch.set_pod(0, pod)
                    tb, aux = compile_batch_terms(
                        mirror.vocab, [pod], b_capacity=batch.capacity
                    )
                    cached = (
                        batch.arrays(), bool(batch.fallback[0]),
                        tb.arrays(), aux, bool(tb.overflow_owners),
                    )
                    if len(self._enc_cache) >= 1024:
                        self._enc_cache.pop(next(iter(self._enc_cache)))
                    self._enc_cache[key] = cached
                else:
                    self.filter_encode_cache["hits"] += 1
                    # LRU refresh: re-insert at the back so a hot spec
                    # (one controller's replicas dominating traffic)
                    # cannot be the first evicted just for being old
                    self._enc_cache[key] = self._enc_cache.pop(key)
                pa_host, pod_fallback, ta_host, aux, term_overflow = cached
                if pod_fallback or term_overflow:
                    return None
                if mirror.pats.overflow_rows:
                    return None
                dev = lambda d: {k: jnp.asarray(v) for k, v in d.items()}
                # incremental device-resident banks: only dirty rows cross
                # the wire (state/cache.py device_arrays)
                na, ea, xa = mirror.device_arrays()
                pa = dev(pa_host)
                ta = dev(ta_host)
                au = dev(aux)
                ids = F.make_ids(mirror.vocab)
                cfg = (
                    SolveConfig(predicates=self.enabled_predicates)
                    if self.enabled_predicates is not None
                    else None
                )
                mask = filter_mask(na, pa, ea, ta, xa, au, ids, config=cfg)
                row = np.asarray(mask[0])
                return {
                    name: bool(row[mirror.row_of[name]])
                    for name in names
                    if name in mirror.row_of
                }
        except Exception:
            return None

    def _resolve(self, args: ExtenderArgs) -> Tuple[Snapshot, List[str], bool]:
        """(snapshot, candidate names, cache_capable_mode)."""
        if args.node_names is not None:
            return self.cache.snapshot, list(args.node_names), True
        nodes = args.nodes or []
        return Snapshot(nodes, []), [n.name for n in nodes], False

    def handle_filter(self, args: ExtenderArgs) -> ExtenderFilterResult:
        pod = args.pod
        if pod is None:
            return ExtenderFilterResult(error="no pod in args")
        snap, names, cache_mode = self._resolve(args)
        feasible: List[str] = []
        failed: Dict[str, str] = {}
        device = (
            self._device_filter(pod, names)
            if cache_mode and len(names) >= self.device_threshold
            else None
        )
        if device is not None:
            for name in names:
                ok = device.get(name)
                if ok:
                    feasible.append(name)
                else:
                    failed[name] = "node unknown" if ok is None else "does not fit"
        else:
            meta = compute_predicate_metadata(pod, snap, enabled=self.enabled_predicates)
            for name in names:
                ni = snap.get(name)
                if ni is None:
                    failed[name] = "node unknown"
                    continue
                ok, reasons = pod_fits_on_node(pod, ni, meta=meta)
                if ok:
                    feasible.append(name)
                else:
                    failed[name] = "; ".join(reasons) if reasons else "does not fit"
        if cache_mode:
            return ExtenderFilterResult(node_names=feasible, failed_nodes=failed)
        keep = set(feasible)
        return ExtenderFilterResult(
            nodes=[n for n in (args.nodes or []) if n.name in keep], failed_nodes=failed
        )

    def handle_prioritize(self, args: ExtenderArgs) -> List[HostPriority]:
        pod = args.pod
        if pod is None:
            return []
        snap, names, _ = self._resolve(args)
        weights = None
        if self.priority_weights is not None:
            from ..oracle.priorities import DEFAULT_PRIORITY_WEIGHTS

            weights = {name: 0 for name in DEFAULT_PRIORITY_WEIGHTS}
            weights.update(dict(self.priority_weights))
        scores = prioritize_nodes(pod, snap, weights=weights, rtcr=self.rtcr)
        # rescale the weighted sum into extender range [0, 10]
        relevant = {n: scores.get(n, 0) for n in names}
        hi = max(relevant.values(), default=0)
        out = []
        for n in names:
            s = relevant.get(n, 0)
            scaled = (s * MAX_EXTENDER_PRIORITY) // hi if hi > 0 else 0
            out.append(HostPriority(host=n, score=int(scaled)))
        return out

    def handle_bind(self, args: ExtenderBindingArgs) -> ExtenderBindingResult:
        if self.bind_fn is None:
            return ExtenderBindingResult(error="binding not supported")
        try:
            self.bind_fn(args)
        except Exception as e:
            return ExtenderBindingResult(error=str(e))
        return ExtenderBindingResult()

    def handle_preemption(self, args: ExtenderPreemptionArgs) -> ExtenderPreemptionResult:
        """Validate the scheduler's victim map against our cache: drop
        candidate nodes we don't know and victims that are already gone
        (core/extender.go ProcessPreemption → convertToMetaVictims)."""
        out: Dict[str, MetaVictims] = {}
        snap = self.cache.snapshot
        if args.node_name_to_meta_victims:
            for node, mv in args.node_name_to_meta_victims.items():
                ni = snap.get(node)
                if ni is None:
                    continue
                known = {p.uid for p in ni.pods}
                uids = [u for u in mv.pod_uids if u in known]
                if len(uids) == len(mv.pod_uids):
                    out[node] = MetaVictims(pod_uids=uids, num_pdb_violations=mv.num_pdb_violations)
        else:
            for node, v in args.node_name_to_victims.items():
                ni = snap.get(node)
                if ni is None:
                    continue
                # same validation as the meta branch: every named victim must
                # still exist on the node (match by UID, or by namespace/name
                # when the sender's UIDs don't line up with ours)
                known_uids = {p.uid for p in ni.pods}
                known_keys = {p.key() for p in ni.pods}
                if all(p.uid in known_uids or p.key() in known_keys for p in v.pods):
                    out[node] = MetaVictims(
                        pod_uids=[p.uid for p in v.pods],
                        num_pdb_violations=v.num_pdb_violations,
                    )
        return ExtenderPreemptionResult(node_name_to_meta_victims=out)

    # -- http plumbing -------------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):  # quiet
                pass

            def _reply(self, obj: dict, code: int = 200) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                n = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self._reply({"Error": "bad json"}, 400)
                    return
                path = self.path.rstrip("/")
                try:
                    if path.endswith("/filter"):
                        res = server.handle_filter(ExtenderArgs.from_json(payload))
                        self._reply(res.to_json())
                    elif path.endswith("/prioritize"):
                        hp = server.handle_prioritize(ExtenderArgs.from_json(payload))
                        self._reply([h.to_json() for h in hp])
                    elif path.endswith("/bind"):
                        res = server.handle_bind(ExtenderBindingArgs.from_json(payload))
                        self._reply(res.to_json())
                    elif path.endswith("/preemption"):
                        res = server.handle_preemption(
                            ExtenderPreemptionArgs.from_json(payload)
                        )
                        self._reply(res.to_json())
                    else:
                        self._reply({"Error": f"unknown verb {path}"}, 404)
                except Exception as e:  # never crash the serving thread
                    self._reply({"Error": str(e)}, 500)

            def do_GET(self) -> None:
                if self.path.rstrip("/").endswith("/healthz"):
                    self._reply({"ok": True})
                else:
                    self._reply({"Error": "POST only"}, 404)

        return Handler
