"""HTTP SchedulerExtender: server (front a real kube-scheduler with the
TPU solver) and client (call out-of-tree extenders from this scheduler).

Wire format: extender/v1 (pkg/scheduler/apis/extender/v1/types.go)."""

from .client import DEFAULT_EXTENDER_TIMEOUT, ExtenderConfig, HTTPExtender
from .server import ExtenderServer
from .types import (
    MAX_EXTENDER_PRIORITY,
    MIN_EXTENDER_PRIORITY,
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderBindingResult,
    ExtenderFilterResult,
    ExtenderPreemptionArgs,
    ExtenderPreemptionResult,
    HostPriority,
    MetaVictims,
    Victims,
)

__all__ = [
    "DEFAULT_EXTENDER_TIMEOUT",
    "ExtenderConfig",
    "HTTPExtender",
    "ExtenderServer",
    "MAX_EXTENDER_PRIORITY",
    "MIN_EXTENDER_PRIORITY",
    "ExtenderArgs",
    "ExtenderBindingArgs",
    "ExtenderBindingResult",
    "ExtenderFilterResult",
    "ExtenderPreemptionArgs",
    "ExtenderPreemptionResult",
    "HostPriority",
    "MetaVictims",
    "Victims",
]
