"""HTTPExtender client — calling OUT to external extenders.

Re-creates core/extender.go:43 (HTTPExtender) and the
algorithm.SchedulerExtender interface (algorithm/scheduler_interface.go:
28-73): Filter/Prioritize/Bind/ProcessPreemption/IsInterested/IsIgnorable,
with the nodeCacheCapable wire modes (:180, :305-331). The Scheduler driver
invokes registered extenders per pod on the host commit path, exactly where
findNodesThatFit (:531-557) and PrioritizeNodes (:813) call them.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..api.types import Node, Pod
from .types import (
    ExtenderArgs,
    ExtenderBindingArgs,
    ExtenderBindingResult,
    ExtenderFilterResult,
    ExtenderPreemptionArgs,
    ExtenderPreemptionResult,
    HostPriority,
    MetaVictims,
    Victims,
)

DEFAULT_EXTENDER_TIMEOUT = 5.0  # core/extender.go DefaultExtenderTimeout


@dataclass
class ExtenderConfig:
    """schedulerapi.ExtenderConfig (pkg/scheduler/api/types.go Extender)."""

    url_prefix: str = ""
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False  # IsIgnorable: failures skip, don't fail the pod
    managed_resources: List[str] = field(default_factory=list)
    timeout_s: float = DEFAULT_EXTENDER_TIMEOUT


class HTTPExtender:
    def __init__(self, config: ExtenderConfig):
        self.config = config

    # -- wire ---------------------------------------------------------------

    def _post(self, verb: str, payload: dict):
        url = self.config.url_prefix.rstrip("/") + "/" + verb
        req = urllib.request.Request(
            url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.config.timeout_s) as resp:
            return json.loads(resp.read() or b"null")

    # -- SchedulerExtender --------------------------------------------------

    def is_ignorable(self) -> bool:
        return self.config.ignorable

    def is_interested(self, pod: Pod) -> bool:
        """IsInterested (core/extender.go:450): with no managed resources,
        every pod; otherwise pods requesting any managed resource."""
        if not self.config.managed_resources:
            return True
        managed = set(self.config.managed_resources)
        for c in pod.containers + pod.init_containers:
            for name in list(c.requests) + list(c.limits):
                if name in managed:
                    return True
        return False

    def supports_filter(self) -> bool:
        return bool(self.config.filter_verb)

    def supports_prioritize(self) -> bool:
        return bool(self.config.prioritize_verb)

    def supports_bind(self) -> bool:
        return bool(self.config.bind_verb)

    def supports_preemption(self) -> bool:
        return bool(self.config.preempt_verb)

    def filter(
        self, pod: Pod, nodes: List[Node]
    ) -> Tuple[List[str], Dict[str, str]]:
        """→ (feasible node names, failed{name: reason}). Raises on wire
        errors (caller honors is_ignorable)."""
        if self.config.node_cache_capable:
            args = ExtenderArgs(pod=pod, node_names=[n.name for n in nodes])
        else:
            args = ExtenderArgs(pod=pod, nodes=nodes)
        res = ExtenderFilterResult.from_json(self._post(self.config.filter_verb, args.to_json()))
        if res.error:
            raise RuntimeError(res.error)
        if res.node_names is not None:
            return list(res.node_names), res.failed_nodes
        return [n.name for n in (res.nodes or [])], res.failed_nodes

    def prioritize(self, pod: Pod, nodes: List[Node]) -> Dict[str, int]:
        """→ {node: score * weight} (PrioritizeNodes :813 applies weight)."""
        if self.config.node_cache_capable:
            args = ExtenderArgs(pod=pod, node_names=[n.name for n in nodes])
        else:
            args = ExtenderArgs(pod=pod, nodes=nodes)
        raw = self._post(self.config.prioritize_verb, args.to_json()) or []
        out: Dict[str, int] = {}
        for d in raw:
            hp = HostPriority.from_json(d)
            out[hp.host] = out.get(hp.host, 0) + hp.score * self.config.weight
        return out

    def bind(self, pod: Pod, node_name: str) -> None:
        args = ExtenderBindingArgs(
            pod_name=pod.name, pod_namespace=pod.namespace, pod_uid=pod.uid, node=node_name
        )
        res = ExtenderBindingResult.from_json(self._post(self.config.bind_verb, args.to_json()))
        if res.error:
            raise RuntimeError(res.error)

    def process_preemption(
        self, pod: Pod, node_to_victims: Dict[str, Victims]
    ) -> Dict[str, MetaVictims]:
        """ProcessPreemption (core/extender.go:119): send the victim map,
        receive the (possibly trimmed) map back."""
        if self.config.node_cache_capable:
            args = ExtenderPreemptionArgs(
                pod=pod,
                node_name_to_meta_victims={
                    n: MetaVictims(
                        pod_uids=[p.uid for p in v.pods],
                        num_pdb_violations=v.num_pdb_violations,
                    )
                    for n, v in node_to_victims.items()
                },
            )
        else:
            args = ExtenderPreemptionArgs(pod=pod, node_name_to_victims=node_to_victims)
        res = ExtenderPreemptionResult.from_json(
            self._post(self.config.preempt_verb, args.to_json())
        )
        return res.node_name_to_meta_victims
