"""Controller manager: shared informers + per-controller worker loops.

The reference's StartControllers (controllermanager.go:500) hands every
initializer a shared informer factory and a stop channel; each controller
runs its own workers draining a workqueue. Same shape here, in-process:
one Informer per kind, one WorkQueue + one worker thread per controller
(the reference defaults to 5 workers per controller; reconciles here are
microseconds against an in-memory store, so one suffices and keeps
event ordering easy to reason about in tests).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List

from ..analysis.lockorder import register_thread_role
from ..client.informer import Informer
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollectorController
from .hpa import HorizontalPodAutoscalerController
from .job import JobController
from .namespace import NamespaceController
from .nodelifecycle import NodeLifecycleController
from .podgc import PodGCController
from .replicaset import ReplicaSetController
from .replication import ReplicationControllerController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .statefulset import StatefulSetController
from .ttlafterfinished import TTLAfterFinishedController
from .workqueue import WorkQueue

logger = logging.getLogger("kubernetes_tpu.controllers.manager")

DEFAULT_CONTROLLERS = (
    "deployment", "replicaset", "job", "nodelifecycle",
    "garbagecollector", "daemonset", "endpoints", "statefulset", "namespace",
    "replication", "podgc", "ttlafterfinished", "cronjob", "disruption",
    "serviceaccount", "resourcequota", "horizontalpodautoscaler",
)


class ControllerManager:
    def __init__(self, api,
                 controllers=DEFAULT_CONTROLLERS,
                 node_monitor_grace_s=None,
                 resync_period_s: float = 1.0,
                 terminated_pod_threshold: int = 0):
        self.api = api
        self.informers: Dict[str, Informer] = {
            "pods": Informer(api, "pods"),
            "nodes": Informer(api, "nodes"),
            "replicasets": Informer(api, "replicasets"),
            "deployments": Informer(api, "deployments"),
            "jobs": Informer(api, "jobs"),
            "statefulsets": Informer(api, "statefulsets"),
            "daemonsets": Informer(api, "daemonsets"),
            "services": Informer(api, "services"),
            "endpoints": Informer(api, "endpoints"),
            "namespaces": Informer(api, "namespaces"),
            "replicationcontrollers": Informer(api, "replicationcontrollers"),
            "cronjobs": Informer(api, "cronjobs"),
            "poddisruptionbudgets": Informer(api, "poddisruptionbudgets"),
            "serviceaccounts": Informer(api, "serviceaccounts"),
            "resourcequotas": Informer(api, "resourcequotas"),
            "horizontalpodautoscalers": Informer(api, "horizontalpodautoscalers"),
            "podmetrics": Informer(api, "podmetrics"),
        }
        self.controllers = []
        self._queues: List[WorkQueue] = []
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # controllers whose clock-driven work (cron schedules, TTL expiry,
        # GC sweeps, metric polls) has no apiserver event: one shared
        # ticker calls their resync_all() every resync_period_s
        self._tickables = []
        self._resync_period_s = resync_period_s
        if "replicaset" in controllers:
            q = WorkQueue()
            self.replicaset = ReplicaSetController(
                api, self.informers["replicasets"], self.informers["pods"], q
            )
            self.controllers.append(self.replicaset)
            self._queues.append(q)
        if "deployment" in controllers:
            q = WorkQueue()
            self.deployment = DeploymentController(
                api, self.informers["deployments"],
                self.informers["replicasets"], q,
            )
            self.controllers.append(self.deployment)
            self._queues.append(q)
        if "job" in controllers:
            q = WorkQueue()
            self.job = JobController(
                api, self.informers["jobs"], self.informers["pods"], q
            )
            self.controllers.append(self.job)
            self._queues.append(q)
        if "statefulset" in controllers:
            q = WorkQueue()
            self.statefulset = StatefulSetController(
                api, self.informers["statefulsets"], self.informers["pods"], q
            )
            self.controllers.append(self.statefulset)
            self._queues.append(q)
        if "daemonset" in controllers:
            q = WorkQueue()
            self.daemonset = DaemonSetController(
                api, self.informers["daemonsets"], self.informers["nodes"],
                self.informers["pods"], q,
            )
            self.controllers.append(self.daemonset)
            self._queues.append(q)
        if "endpoints" in controllers:
            q = WorkQueue()
            self.endpoints = EndpointsController(
                api, self.informers["services"], self.informers["pods"], q
            )
            self.controllers.append(self.endpoints)
            self._queues.append(q)
        if "garbagecollector" in controllers:
            q = WorkQueue()
            self.garbagecollector = GarbageCollectorController(
                api, self.informers, q
            )
            self.controllers.append(self.garbagecollector)
            self._queues.append(q)
        if "namespace" in controllers:
            q = WorkQueue()
            self.namespace = NamespaceController(
                api, self.informers["namespaces"], q
            )
            self.controllers.append(self.namespace)
            self._queues.append(q)
        if "replication" in controllers:
            q = WorkQueue()
            self.replication = ReplicationControllerController(
                api, self.informers["replicationcontrollers"],
                self.informers["pods"], q,
            )
            self.controllers.append(self.replication)
            self._queues.append(q)
        if "podgc" in controllers:
            q = WorkQueue()
            self.podgc = PodGCController(
                api, self.informers["pods"], self.informers["nodes"], q,
                terminated_pod_threshold=terminated_pod_threshold,
            )
            self.controllers.append(self.podgc)
            self._queues.append(q)
            self._tickables.append(self.podgc)
        if "ttlafterfinished" in controllers:
            q = WorkQueue()
            self.ttlafterfinished = TTLAfterFinishedController(
                api, self.informers["jobs"], q
            )
            self.controllers.append(self.ttlafterfinished)
            self._queues.append(q)
            self._tickables.append(self.ttlafterfinished)
        if "cronjob" in controllers:
            q = WorkQueue()
            self.cronjob = CronJobController(
                api, self.informers["cronjobs"], self.informers["jobs"], q
            )
            self.controllers.append(self.cronjob)
            self._queues.append(q)
            self._tickables.append(self.cronjob)
        if "disruption" in controllers:
            q = WorkQueue()
            self.disruption = DisruptionController(
                api, self.informers["poddisruptionbudgets"],
                self.informers["pods"], q,
            )
            self.controllers.append(self.disruption)
            self._queues.append(q)
        if "serviceaccount" in controllers:
            q = WorkQueue()
            self.serviceaccount = ServiceAccountController(
                api, self.informers["namespaces"],
                self.informers["serviceaccounts"], q,
            )
            self.controllers.append(self.serviceaccount)
            self._queues.append(q)
        if "resourcequota" in controllers:
            q = WorkQueue()
            self.resourcequota = ResourceQuotaController(
                api, self.informers["resourcequotas"],
                self.informers["pods"], q,
            )
            self.controllers.append(self.resourcequota)
            self._queues.append(q)
            # count/{kind} usage has no per-kind watch; the periodic
            # resync refreshes it after non-pod deletes (the reference
            # quota controller runs a full resync for the same reason)
            self._tickables.append(self.resourcequota)
        if "horizontalpodautoscaler" in controllers:
            q = WorkQueue()
            self.horizontalpodautoscaler = HorizontalPodAutoscalerController(
                api, self.informers["horizontalpodautoscalers"],
                self.informers["pods"], self.informers["podmetrics"], q,
            )
            self.controllers.append(self.horizontalpodautoscaler)
            self._queues.append(q)
            self._tickables.append(self.horizontalpodautoscaler)
        if "nodelifecycle" in controllers:
            q = WorkQueue()
            self.nodelifecycle = NodeLifecycleController(
                api, self.informers["nodes"], self.informers["pods"], q,
                monitor_grace_s=node_monitor_grace_s,
            )
            self.controllers.append(self.nodelifecycle)
            self._queues.append(q)
            if node_monitor_grace_s:
                t = threading.Thread(
                    target=self._monitor_loop,
                    args=(self.nodelifecycle, node_monitor_grace_s / 4.0),
                    name="node-monitor", daemon=True,
                )
                self._monitor_thread = t

    def start(self) -> "ControllerManager":
        for c in self.controllers:
            c.register()
        if getattr(self, "_monitor_thread", None) is not None:
            self._monitor_thread.start()
        for inf in self.informers.values():
            inf.start()
        for inf in self.informers.values():
            inf.wait_for_sync()
        for c, q in zip(self.controllers, self._queues):
            t = threading.Thread(
                target=self._worker, args=(c, q),
                name=f"ctrl-{type(c).__name__}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        if self._tickables:
            t = threading.Thread(target=self._tick_loop, name="ctrl-resync", daemon=True)
            t.start()
            self._threads.append(t)
        return self

    # ktpu: thread-entry(controller) the shared resync ticker
    def _tick_loop(self) -> None:
        register_thread_role("controller")
        while not self._stop.wait(self._resync_period_s):
            for c in self._tickables:
                try:
                    c.resync_all()
                except Exception:
                    logger.exception("resync tick failed for %s", type(c).__name__)

    # ktpu: thread-entry(controller)
    def _monitor_loop(self, controller, period_s: float) -> None:
        """monitorNodeHealth's clock: staleness has no apiserver event,
        so every period each node re-syncs."""
        register_thread_role("controller")
        while not self._stop.wait(period_s):
            try:
                controller.resync_all()
            except Exception:
                logger.exception("node monitor tick failed")

    # ktpu: thread-entry(controller) one reconcile worker per controller
    def _worker(self, controller, queue: WorkQueue) -> None:
        register_thread_role("controller")
        while not self._stop.is_set():
            key = queue.get(timeout=0.2)
            if key is None:
                continue
            try:
                controller.sync(key)
            except Exception:  # a bad object must not kill the loop
                logger.exception("reconcile %s failed", key)
            finally:
                queue.done(key)

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test helper: block until every workqueue is drained."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(len(q) == 0 for q in self._queues):
                time.sleep(0.05)  # let in-flight sync() finish
                if all(len(q) == 0 for q in self._queues):
                    return True
            time.sleep(0.02)
        return False

    def stop(self) -> None:
        self._stop.set()
        for q in self._queues:
            q.shutdown()
        for t in self._threads:
            t.join(timeout=2.0)
        for inf in self.informers.values():
            inf.stop()
