"""DaemonSet controller: one pod per eligible node, scheduled by the
DEFAULT scheduler.

Reference: pkg/controller/daemon/daemon_controller.go. In this reference
era (ScheduleDaemonSetPods on by default) the controller does NOT bind
pods itself: each daemon pod carries a node-affinity pin
(util.ReplaceDaemonSetPodNodeNameNodeAffinity — a required matchFields
metadata.name In [node] term) plus the standard daemon tolerations
(util.AddOrUpdateDaemonPodTolerations: not-ready/unreachable NoExecute,
unschedulable/disk-pressure/memory-pressure NoSchedule), and the default
scheduler places it — taints, resources, and the pin all flow through the
normal Filter path (our device mask's OP_NAME_IN handles the pin).

Eligibility (nodeShouldRunDaemonPod, simplified to the scheduling-visible
parts): the template's nodeSelector must match the node's labels; taint
tolerance is the SCHEDULER's job (the added tolerations express the
daemon contract).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api.types import (
    Affinity,
    DaemonSet,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    Toleration,
)
from .podowner import new_child_pod, owned_by

logger = logging.getLogger("kubernetes_tpu.controllers.daemonset")

DAEMON_TOLERATIONS = [
    Toleration(key="node.kubernetes.io/not-ready", operator="Exists", effect="NoExecute"),
    Toleration(key="node.kubernetes.io/unreachable", operator="Exists", effect="NoExecute"),
    Toleration(key="node.kubernetes.io/unschedulable", operator="Exists", effect="NoSchedule"),
    Toleration(key="node.kubernetes.io/disk-pressure", operator="Exists", effect="NoSchedule"),
    Toleration(key="node.kubernetes.io/memory-pressure", operator="Exists", effect="NoSchedule"),
]


def _node_pin(node_name: str) -> Affinity:
    """ReplaceDaemonSetPodNodeNameNodeAffinity: required matchFields
    metadata.name In [node]."""
    return Affinity(
        node_affinity=NodeAffinity(
            required=NodeSelector(
                node_selector_terms=[
                    NodeSelectorTerm(
                        match_fields=[
                            NodeSelectorRequirement(
                                key="metadata.name", operator="In", values=[node_name]
                            )
                        ]
                    )
                ]
            )
        )
    )


class DaemonSetController:
    def __init__(self, api, ds_informer, node_informer, pod_informer, queue):
        self.api = api
        self.ds_informer = ds_informer
        self.node_informer = node_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.ds_informer.add_event_handler(
            on_add=lambda ds: self.queue.add(ds.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda ds: self.queue.add(ds.key()),
        )
        # node membership AND eligibility changes re-reconcile every
        # daemonset (daemon_controller.go updateNode re-runs
        # nodeShouldRunDaemonPod when labels/taints change)
        self.node_informer.add_event_handler(
            on_add=lambda n: self._enqueue_all(),
            on_update=lambda old, new: (
                self._enqueue_all()
                if old.labels != new.labels or old.taints != new.taints
                else None
            ),
            on_delete=lambda n: self._enqueue_all(),
        )
        self.pod_informer.add_event_handler(
            on_delete=lambda p: self._enqueue_owner(p),
        )

    def _enqueue_all(self) -> None:
        for ds in self.ds_informer.list():
            self.queue.add(ds.key())

    def _enqueue_owner(self, pod: Pod) -> None:
        for ref in pod.owner_references:
            if ref.get("controller") and ref.get("kind") == "DaemonSet":
                self.queue.add(f"{pod.namespace}/{ref.get('name')}")
                return

    def _eligible(self, ds: DaemonSet, node: Node) -> bool:
        tmpl = ds.template or Pod()
        return all(node.labels.get(k) == v for k, v in tmpl.node_selector.items())

    def sync(self, key: str) -> None:
        self.sync_count += 1
        ds: Optional[DaemonSet] = self.ds_informer.get(key)
        if ds is None:
            return  # deletion cascade is the GC's job
        nodes = {n.name: n for n in self.node_informer.list()}
        want = {nm for nm, n in nodes.items() if self._eligible(ds, n)}
        have: dict = {}
        terminal: dict = {}  # Failed/Succeeded daemon pods holding the name
        for p in self.pod_informer.list():
            if not owned_by(p, ds.uid):
                continue
            target = p.node_name or _pinned_node(p)
            if p.phase in ("Failed", "Succeeded"):
                terminal.setdefault(target, []).append(p)
                continue
            have.setdefault(target, []).append(p)
        for nm in sorted(want):
            if nm not in have:
                dead = terminal.get(nm)
                if dead:
                    # the deterministic name {ds}-{node} is still held by a
                    # terminal pod — free it first (delete event re-syncs)
                    for p in dead:
                        try:
                            self.api.delete("pods", p.key())
                        except KeyError:
                            pass
                    continue
                self.api.create("pods", self._daemon_pod(ds, nm))
        for nm, pods in have.items():
            surplus: List[Pod] = pods[1:] if nm in want else pods
            for p in surplus:
                try:
                    self.api.delete("pods", p.key())
                except KeyError:
                    pass

    def _daemon_pod(self, ds: DaemonSet, node_name: str) -> Pod:
        pod = new_child_pod(ds.template, "DaemonSet", ds.name, ds.uid, ds.namespace)
        pod.name = f"{ds.name}-{node_name}"
        pod.affinity = _node_pin(node_name)
        pod.tolerations = list((ds.template.tolerations if ds.template else [])) + [
            t for t in DAEMON_TOLERATIONS
        ]
        return pod


def _pinned_node(pod: Pod) -> str:
    a = pod.affinity
    try:
        for term in a.node_affinity.required.node_selector_terms:
            for req in term.match_fields:
                if req.key == "metadata.name" and req.operator == "In" and req.values:
                    return req.values[0]
    except AttributeError:
        pass
    return ""
