"""Pod garbage collector (pkg/controller/podgc/gc_controller.go).

Three sweeps, run on the manager's resync tick (the reference runs
gcCheckPeriod=20s; the period is the manager's knob here):

* gcTerminated: when the number of terminated pods (Succeeded/Failed)
  exceeds `terminated_pod_threshold`, delete the oldest beyond the
  threshold (threshold <= 0 disables, matching the reference default
  of 12500 being flag-set).
* gcOrphaned: pods bound to a node that no longer exists are deleted —
  the kubelet that would report them is gone (gc_controller.go:129).
* gcUnscheduledTerminating: pods with a deletionTimestamp that never got
  a node can never terminate gracefully; force-delete (gc_controller.go:160).
"""

from __future__ import annotations

import logging

logger = logging.getLogger("kubernetes_tpu.controllers.podgc")


class PodGCController:
    def __init__(self, api, pod_informer, node_informer, queue,
                 terminated_pod_threshold: int = 0):
        self.api = api
        self.pod_informer = pod_informer
        self.node_informer = node_informer
        self.queue = queue
        self.terminated_pod_threshold = terminated_pod_threshold
        self.sync_count = 0
        self.deleted_count = 0

    def register(self) -> None:
        # a node deletion can orphan pods immediately; otherwise the
        # periodic resync drives the sweeps
        self.node_informer.add_event_handler(
            on_delete=lambda n: self.queue.add("gc"),
        )

    def resync_all(self) -> None:
        self.queue.add("gc")

    def _delete(self, pod) -> None:
        try:
            self.api.delete("pods", pod.key())
            self.deleted_count += 1
        except KeyError:
            pass

    def sync(self, key: str) -> None:
        self.sync_count += 1
        pods = self.pod_informer.list()
        node_names = {n.name for n in self.node_informer.list()}

        terminated = [p for p in pods if p.phase in ("Succeeded", "Failed")]
        if 0 < self.terminated_pod_threshold < len(terminated):
            excess = len(terminated) - self.terminated_pod_threshold
            for p in sorted(terminated, key=lambda p: p.creation_timestamp)[:excess]:
                self._delete(p)

        for p in pods:
            if p.node_name and p.node_name not in node_names:
                # informer caches can lag each other (pod ADDED applied
                # before its node's ADDED): confirm absence against the
                # apiserver before the destructive delete, as the
                # reference does (gc_controller.go:142 live node get)
                try:
                    self.api.get("nodes", p.node_name)
                    continue  # node exists; the informer was behind
                except KeyError:
                    pass
                logger.info("podgc: orphaned pod %s (node %s gone)", p.key(), p.node_name)
                self._delete(p)
            elif p.deletion_timestamp is not None and not p.node_name:
                logger.info("podgc: unscheduled terminating pod %s", p.key())
                self._delete(p)
