"""ReplicationController controller (pkg/controller/replication/
replication_controller.go — in the reference this is literally an adapter
that reuses the ReplicaSet reconciler over converted RC objects;
conversion.go wraps the clientset). Same move here: the RC kind decodes
its v1 map selector into a LabelSelector, and the reconciler subclasses
ReplicaSetController with the RC owner kind."""

from __future__ import annotations

from .replicaset import ReplicaSetController


class ReplicationControllerController(ReplicaSetController):
    OWNER_KIND = "ReplicationController"
