"""Node lifecycle controller (pkg/controller/nodelifecycle/).

The reference's monitorNodeHealth watches each node's Ready condition and
manages the not-ready/unreachable taints
(node_lifecycle_controller.go:~770 processTaintBaseEviction +
nodetree taint helpers); its NoExecute taint manager
(scheduler/taint_manager.go) evicts pods lacking a matching toleration.
Condensed here into one reconcile per node:

  Ready != "True"  → ensure node.kubernetes.io/not-ready {NoSchedule,
                     NoExecute} taints, then evict (delete) every bound pod
                     without a toleration for them — the ReplicaSet
                     controller replaces the evicted replicas elsewhere.
  Ready == "True"  → remove both taints (the scheduler's eventhandlers see
                     the node update and flush unschedulable pods back to
                     the active queue — MoveAllToActiveQueue semantics).

Grace periods (node-monitor-grace-period etc.) collapse to immediate
reaction: the fake apiserver's conditions ARE the health signal (no
heartbeat staleness to debounce).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..api.types import (
    Node,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    Taint,
    tolerations_tolerate_taint,
)
from ..apiserver.store import ConflictError, NotFoundError

logger = logging.getLogger("kubernetes_tpu.controllers.nodelifecycle")

TAINT_NOT_READY = "node.kubernetes.io/not-ready"


def _ready_condition(node: Node) -> bool:
    for c in node.conditions:
        if c.get("type") == "Ready":
            return c.get("status") == "True"
    return True  # no conditions reported: treat as healthy (fresh sim node)


class NodeLifecycleController:
    def __init__(self, api, node_informer, pod_informer, queue,
                 monitor_grace_s: Optional[float] = None):
        self.api = api
        self.node_informer = node_informer
        self.pod_informer = pod_informer
        self.queue = queue
        # node-lease staleness threshold (node-monitor-grace-period);
        # falsy disables the monitor (purely condition-driven, the
        # pre-kubemark behavior)
        self.monitor_grace_s = monitor_grace_s or None
        self.evictions = 0  # observability for tests

    def _heartbeat_stale(self, name: str) -> bool:
        """monitorNodeHealth's grace-period half over NodeLease objects: a
        node whose kubelet renews `node-<name>` in the leases kind goes
        unready once the renew time is older than the grace period. Nodes
        without a lease are status-driven only (static sim nodes exempt)."""
        if self.monitor_grace_s is None:
            return False
        try:
            rec = self.api.get("leases", f"node-{name}")
        except (KeyError, NotFoundError):
            return False
        return time.time() - rec.renew_time > self.monitor_grace_s

    def _ready(self, node: Node) -> bool:
        if self._heartbeat_stale(node.name):
            return False
        return _ready_condition(node)

    @staticmethod
    def _untaint(node: Node) -> None:
        node.taints = [t for t in node.taints if t.key != TAINT_NOT_READY]

    def _taint_mutator(self, stale: bool):
        def mutate(node: Node) -> None:
            if any(t.key == TAINT_NOT_READY for t in node.taints):
                return
            node.taints = list(node.taints) + [
                Taint(key=TAINT_NOT_READY, effect=TAINT_NO_SCHEDULE),
                Taint(key=TAINT_NOT_READY, effect=TAINT_NO_EXECUTE),
            ]
            if stale:
                # record WHY (monitorNodeHealth writes Unknown when the
                # kubelet stops reporting)
                node.conditions = [
                    c for c in node.conditions if c.get("type") != "Ready"
                ] + [{"type": "Ready", "status": "Unknown",
                      "reason": "NodeStatusUnknown"}]
        return mutate

    def _cas_node(self, name: str, mutate) -> None:
        """Read-modify-write against the AUTHORITATIVE store copy with a
        resourceVersion precondition: writing the informer's (possibly
        stale) object back blind would clobber concurrent writers'
        fields."""
        for _ in range(5):
            try:
                node = self.api.get("nodes", name)
            except (KeyError, NotFoundError):
                return
            mutate(node)
            try:
                self.api.update("nodes", node, check_rv=True)
                return
            except ConflictError:
                continue

    def resync_all(self) -> None:
        """Periodic monitor tick (monitorNodeHealth): re-enqueue every
        node so staleness is noticed without an apiserver event."""
        for n in self.node_informer.list():
            self.queue.add(n.name)

    def register(self) -> None:
        self.node_informer.add_event_handler(
            on_add=lambda n: self.queue.add(n.name),
            on_update=lambda old, new: self.queue.add(new.name),
        )
        # a pod BINDING to a node that is already unready must be evicted
        # too (the reference's NoExecute taint manager watches pod events,
        # taint_manager.go PodUpdated) — re-sync the hosting node
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._pod_event(p),
            on_update=lambda old, new: self._pod_event(new),
        )

    def _pod_event(self, pod) -> None:
        if not pod.node_name:
            return
        node = self.node_informer.get(pod.node_name)
        if node is not None and not self._ready(node):
            self.queue.add(node.name)

    def sync(self, name: str) -> None:
        node: Optional[Node] = self.node_informer.get(name)
        if node is None:
            return
        tainted = any(t.key == TAINT_NOT_READY for t in node.taints)
        if self._ready(node):
            if tainted:
                self._cas_node(name, self._untaint)
            return
        if not tainted:
            self._cas_node(name, self._taint_mutator(self._heartbeat_stale(name)))
        # NoExecute eviction: every pod bound here without a toleration
        no_exec = Taint(key=TAINT_NOT_READY, effect=TAINT_NO_EXECUTE)
        for p in self.pod_informer.list():
            if p.node_name != name:
                continue
            if tolerations_tolerate_taint(p.tolerations, no_exec):
                continue
            try:
                self.api.delete("pods", p.key())
                self.evictions += 1
            except KeyError:
                pass
