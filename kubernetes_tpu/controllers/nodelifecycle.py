"""Node lifecycle controller (pkg/controller/nodelifecycle/).

The reference's monitorNodeHealth watches each node's Ready condition and
manages the not-ready/unreachable taints
(node_lifecycle_controller.go:~770 processTaintBaseEviction +
nodetree taint helpers); its NoExecute taint manager
(scheduler/taint_manager.go) evicts pods lacking a matching toleration.
Condensed here into one reconcile per node:

  Ready != "True"  → ensure node.kubernetes.io/not-ready {NoSchedule,
                     NoExecute} taints, then evict (delete) every bound pod
                     without a toleration for them — the ReplicaSet
                     controller replaces the evicted replicas elsewhere.
  Ready == "True"  → remove both taints (the scheduler's eventhandlers see
                     the node update and flush unschedulable pods back to
                     the active queue — MoveAllToActiveQueue semantics).

Grace periods (node-monitor-grace-period etc.) collapse to immediate
reaction: the fake apiserver's conditions ARE the health signal (no
heartbeat staleness to debounce).
"""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import (
    Node,
    TAINT_NO_EXECUTE,
    TAINT_NO_SCHEDULE,
    Taint,
    tolerations_tolerate_taint,
)

logger = logging.getLogger("kubernetes_tpu.controllers.nodelifecycle")

TAINT_NOT_READY = "node.kubernetes.io/not-ready"


def _ready(node: Node) -> bool:
    for c in node.conditions:
        if c.get("type") == "Ready":
            return c.get("status") == "True"
    return True  # no conditions reported: treat as healthy (fresh sim node)


class NodeLifecycleController:
    def __init__(self, api, node_informer, pod_informer, queue):
        self.api = api
        self.node_informer = node_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.evictions = 0  # observability for tests

    def register(self) -> None:
        self.node_informer.add_event_handler(
            on_add=lambda n: self.queue.add(n.name),
            on_update=lambda old, new: self.queue.add(new.name),
        )
        # a pod BINDING to a node that is already unready must be evicted
        # too (the reference's NoExecute taint manager watches pod events,
        # taint_manager.go PodUpdated) — re-sync the hosting node
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._pod_event(p),
            on_update=lambda old, new: self._pod_event(new),
        )

    def _pod_event(self, pod) -> None:
        if not pod.node_name:
            return
        node = self.node_informer.get(pod.node_name)
        if node is not None and not _ready(node):
            self.queue.add(node.name)

    def sync(self, name: str) -> None:
        node: Optional[Node] = self.node_informer.get(name)
        if node is None:
            return
        tainted = any(t.key == TAINT_NOT_READY for t in node.taints)
        if _ready(node):
            if tainted:
                node.taints = [t for t in node.taints if t.key != TAINT_NOT_READY]
                self.api.update("nodes", node)
            return
        if not tainted:
            node.taints = list(node.taints) + [
                Taint(key=TAINT_NOT_READY, effect=TAINT_NO_SCHEDULE),
                Taint(key=TAINT_NOT_READY, effect=TAINT_NO_EXECUTE),
            ]
            self.api.update("nodes", node)
        # NoExecute eviction: every pod bound here without a toleration
        no_exec = Taint(key=TAINT_NOT_READY, effect=TAINT_NO_EXECUTE)
        for p in self.pod_informer.list():
            if p.node_name != name:
                continue
            if tolerations_tolerate_taint(p.tolerations, no_exec):
                continue
            try:
                self.api.delete("pods", p.key())
                self.evictions += 1
            except KeyError:
                pass
