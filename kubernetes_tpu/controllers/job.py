"""Job controller (pkg/controller/job/job_controller.go).

Run-to-completion workloads: a Job keeps `parallelism` pods running until
`completions` pods have Succeeded (manageJob/syncJob semantics). Failed
pods are replaced (backoffLimit collapses to "always retry" — the
reference's exponential job backoff protects a real apiserver this
in-process store doesn't need); Succeeded pods count toward completion
and are never replaced. When completions are reached, remaining active
pods are left to finish (no active deletion — matching the reference's
non-indexed default where success is counted, not truncated).

The sim's hollow kubelets mark pods Running; tests drive Succeeded/Failed
transitions the way a real workload would report them.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api.types import Job, Pod
from .podowner import deletion_rank, new_child_pod, owned_by

logger = logging.getLogger("kubernetes_tpu.controllers.job")


class JobController:
    def __init__(self, api, job_informer, pod_informer, queue):
        self.api = api
        self.job_informer = job_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.job_informer.add_event_handler(
            on_add=lambda j: self.queue.add(j.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_owner(p),
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=lambda p: self._enqueue_owner(p),
        )

    def _enqueue_owner(self, pod: Pod) -> None:
        for ref in pod.owner_references:
            if ref.get("controller") and ref.get("kind") == "Job":
                self.queue.add(f"{pod.namespace}/{ref.get('name')}")
                return

    def sync(self, key: str) -> None:
        self.sync_count += 1
        job: Optional[Job] = self.job_informer.get(key)
        if job is None:
            return
        active: List[Pod] = []
        succeeded = 0
        for p in self.pod_informer.list():
            if not owned_by(p, job.uid):
                continue
            if p.phase == "Succeeded":
                succeeded += 1
            elif p.phase != "Failed":
                active.append(p)
        if succeeded >= job.completions:
            return  # done; stragglers run to their own completion
        # keep `parallelism` active, bounded by the completions still needed
        want_active = min(job.parallelism, job.completions - succeeded)
        diff = want_active - len(active)
        if diff > 0:
            for _ in range(diff):
                self.api.create("pods", self._new_pod(job))
        elif diff < 0:
            # parallelism was lowered: trim pending pods first
            victims = sorted(active, key=deletion_rank)
            for p in victims[:-diff]:
                try:
                    self.api.delete("pods", p.key())
                except KeyError:
                    pass

    def _new_pod(self, job: Job) -> Pod:
        return new_child_pod(job.template, "Job", job.name, job.uid, job.namespace)
