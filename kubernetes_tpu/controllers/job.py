"""Job controller (pkg/controller/job/job_controller.go).

Run-to-completion workloads: a Job keeps `parallelism` pods running until
`completions` pods have Succeeded (manageJob/syncJob semantics). Failed
pods are replaced (backoffLimit collapses to "always retry" — the
reference's exponential job backoff protects a real apiserver this
in-process store doesn't need); Succeeded pods count toward completion
and are never replaced. When completions are reached, remaining active
pods are left to finish (no active deletion — matching the reference's
non-indexed default where success is counted, not truncated).

The sim's hollow kubelets mark pods Running; tests drive Succeeded/Failed
transitions the way a real workload would report them.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api.types import Job, Pod
from .podowner import deletion_rank, new_child_pod, owned_by

logger = logging.getLogger("kubernetes_tpu.controllers.job")


class JobController:
    def __init__(self, api, job_informer, pod_informer, queue):
        self.api = api
        self.job_informer = job_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.job_informer.add_event_handler(
            on_add=lambda j: self.queue.add(j.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_owner(p),
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=lambda p: self._enqueue_owner(p),
        )

    def _enqueue_owner(self, pod: Pod) -> None:
        for ref in pod.owner_references:
            if ref.get("controller") and ref.get("kind") == "Job":
                self.queue.add(f"{pod.namespace}/{ref.get('name')}")
                return

    def sync(self, key: str) -> None:
        self.sync_count += 1
        job: Optional[Job] = self.job_informer.get(key)
        if job is None:
            return
        active: List[Pod] = []
        succeeded = 0
        failed = 0
        for p in self.pod_informer.list():
            if not owned_by(p, job.uid):
                continue
            if p.phase == "Succeeded":
                succeeded += 1
            elif p.phase == "Failed":
                failed += 1
            else:
                active.append(p)
        # a job that has completed STAYS completed even if its Succeeded
        # pods are later garbage-collected (the reference's Complete
        # condition is terminal; completionTime is never cleared)
        finished = job.completion_time is not None or succeeded >= job.completions
        self._update_status(job, len(active), succeeded, failed, finished)
        if finished:
            return  # done; stragglers run to their own completion
        # keep `parallelism` active, bounded by the completions still needed
        want_active = min(job.parallelism, job.completions - succeeded)
        diff = want_active - len(active)
        if diff > 0:
            for _ in range(diff):
                self.api.create("pods", self._new_pod(job))
        elif diff < 0:
            # parallelism was lowered: trim pending pods first
            victims = sorted(active, key=deletion_rank)
            for p in victims[:-diff]:
                try:
                    self.api.delete("pods", p.key())
                except KeyError:
                    pass

    def _new_pod(self, job: Job) -> Pod:
        return new_child_pod(job.template, "Job", job.name, job.uid, job.namespace)

    def _update_status(self, job: Job, active: int, succeeded: int, failed: int,
                       finished: bool) -> None:
        """syncJob's status write (job_controller.go updateJobStatus):
        counts + completionTime stamped once when completions are reached.
        Skipped when nothing changed so the MODIFIED→enqueue→sync cycle
        settles instead of looping; completionTime is write-once, so a
        finished job whose counts are stable never re-writes."""
        counts_equal = (job.active == active and job.succeeded == succeeded
                        and job.failed == failed)
        needs_time = finished and job.completion_time is None
        if counts_equal and not needs_time:
            return
        import copy as _copy
        import time as _time

        cached = self.job_informer.get(job.key())
        if cached is None:
            return
        updated = _copy.copy(cached)  # never mutate the informer's object
        updated.active = active
        updated.succeeded = succeeded
        updated.failed = failed
        if finished and updated.completion_time is None:
            updated.completion_time = _time.time()
        try:
            self.api.update("jobs", updated)
        except KeyError:
            pass
