"""ResourceQuota controller (pkg/controller/resourcequota/
resource_quota_controller.go): keeps each quota's status.used in sync
with actual usage in its namespace. Enforcement happens at admission
(apiserver/admission.py ResourceQuotaAdmission); this loop is the
status reconciler that replenishes usage when objects are deleted.

Evaluated resources (the core evaluator set, quota/v1/evaluator/core):
  pods            — count of non-terminal pods
  requests.cpu    — sum of pod cpu requests (milli)
  requests.memory — sum of pod memory requests (bytes)
  count/{kind}    — object counts for any stored kind
"""

from __future__ import annotations

import copy
import logging
from typing import Dict, Optional

from ..api.types import Pod, ResourceQuota

logger = logging.getLogger("kubernetes_tpu.controllers.resourcequota")


def compute_usage(api, namespace: str, hard: Dict[str, int],
                  pods=None) -> Dict[str, int]:
    """Usage for exactly the resources the quota constrains (the
    reference's evaluators also only measure matched resources).
    `pods` lets callers holding an informer pass its cache instead of
    paying a deep-copied store list per sync."""
    used: Dict[str, int] = {}
    pod_keys = [k for k in hard if k in ("pods", "requests.cpu", "requests.memory")]
    if pod_keys:
        if pods is None:
            pods, _ = api.list("pods")
        live = [p for p in pods
                if p.namespace == namespace and p.phase not in ("Succeeded", "Failed")]
        for k in pod_keys:
            if k == "pods":
                used[k] = len(live)
            else:
                resource = k.split(".", 1)[1]
                used[k] = sum(p.resource_request().get(resource, 0) for p in live)
    for k in hard:
        if k.startswith("count/"):
            kind = k.split("/", 1)[1]
            objs, _ = api.list(kind)
            used[k] = sum(1 for o in objs if getattr(o, "namespace", None) == namespace)
    return used


class ResourceQuotaController:
    def __init__(self, api, quota_informer, pod_informer, queue):
        self.api = api
        self.quota_informer = quota_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.quota_informer.add_event_handler(
            on_add=lambda q: self.queue.add(q.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_ns(p),
            on_update=lambda old, new: self._enqueue_ns(new),
            on_delete=lambda p: self._enqueue_ns(p),
        )

    def _enqueue_ns(self, pod: Pod) -> None:
        for q in self.quota_informer.list():
            if q.namespace == pod.namespace:
                self.queue.add(q.key())

    def resync_all(self) -> None:
        for q in self.quota_informer.list():
            self.queue.add(q.key())

    def sync(self, key: str) -> None:
        self.sync_count += 1
        quota: Optional[ResourceQuota] = self.quota_informer.get(key)
        if quota is None:
            return
        used = compute_usage(self.api, quota.namespace, quota.hard,
                             pods=self.pod_informer.list())
        if used == quota.used:
            return
        updated = copy.copy(quota)
        updated.used = used
        try:
            self.api.update("resourcequotas", updated)
        except KeyError:
            pass
