"""Deployment controller (pkg/controller/deployment/deployment_controller.go).

The reconcile subset that closes the workload-management story on top of
the ReplicaSet controller: a Deployment owns ReplicaSets keyed by a
TEMPLATE HASH (getNewReplicaSet / rsutil.GetPodTemplateSpecHash); the
active RS is scaled to .spec.replicas and every RS with a different
template hash is scaled to zero — the "Recreate"-shaped rollout (the
reference's default RollingUpdate maxSurge/maxUnavailable scheduling is
a progressive version of the same two scale operations; surge windows
are out of scope here and documented as such).

So: edit the Deployment's template → a new hash → a new RS appears and
the old one drains; the ReplicaSet controller + scheduler + (hollow)
kubelets do the rest.
"""

from __future__ import annotations

import hashlib
import logging
from dataclasses import replace
from typing import Optional

from ..api.types import Deployment, ReplicaSet

logger = logging.getLogger("kubernetes_tpu.controllers.deployment")


def template_hash(dep: Deployment) -> str:
    """Stable hash of the pod template (rsutil.ComputeHash analogue): EVERY
    spec-shaping field (an edit to any of them must produce a new hash and
    therefore a new generation), via value-based dataclass reprs."""
    t = dep.template
    if t is None:
        return "empty"
    basis = repr((
        sorted(t.labels.items()),
        sorted(t.annotations.items()),
        t.containers,
        t.init_containers,
        t.overhead,
        t.tolerations,
        sorted(t.node_selector.items()),
        t.affinity,
        t.topology_spread_constraints,
        t.priority,
        t.priority_class_name,
        t.host_network,
        t.volumes,
        t.scheduler_name,
    ))
    return hashlib.sha1(basis.encode()).hexdigest()[:10]


def _owned(rs: ReplicaSet, dep: Deployment) -> bool:
    """ownerReference (controller uid) match — NOT name prefixes, which
    collide between deployments like `web` and `web-api`."""
    return any(
        ref.get("controller") and ref.get("uid") == dep.uid
        for ref in rs.owner_references
    )


class DeploymentController:
    def __init__(self, api, dep_informer, rs_informer, queue):
        self.api = api
        self.dep_informer = dep_informer
        self.rs_informer = rs_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.dep_informer.add_event_handler(
            on_add=lambda d: self.queue.add(d.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda d: self.queue.add(d.key()),
        )
        # RS churn re-syncs the owning deployment (getDeploymentsForReplicaSet)
        self.rs_informer.add_event_handler(
            on_add=lambda rs: self._enqueue_owner(rs),
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=lambda rs: self._enqueue_owner(rs),
        )

    def _enqueue_owner(self, rs: ReplicaSet) -> None:
        uids = {
            ref.get("uid")
            for ref in rs.owner_references
            if ref.get("controller") and ref.get("kind") == "Deployment"
        }
        if not uids:
            return
        for d in self.dep_informer.list():
            if d.uid in uids:
                self.queue.add(d.key())
                return

    def sync(self, key: str) -> None:
        self.sync_count += 1
        dep: Optional[Deployment] = self.dep_informer.get(key)
        if dep is None:
            return  # deleted: owned RSs keep running (no GC, as with RS→pods)
        want = f"{dep.name}-{template_hash(dep)}"
        active: Optional[ReplicaSet] = None
        for rs in self.rs_informer.list():
            if not _owned(rs, dep):
                continue
            if rs.name == want:
                active = rs
            elif rs.replicas != 0:
                # old template generation: drain it (the RS controller
                # deletes its surplus pods, pending-first). Update a COPY:
                # informer store objects are shared with the RS controller
                # and must only change when the apiserver accepts the write
                self.api.update("replicasets", replace(rs, replicas=0))
        if active is None:
            self.api.create("replicasets", ReplicaSet(
                name=want,
                namespace=dep.namespace,
                replicas=dep.replicas,
                selector=dep.selector,
                template=dep.template,
                owner_references=[{
                    "uid": dep.uid, "controller": True,
                    "kind": "Deployment", "name": dep.name,
                }],
            ))
        elif active.replicas != dep.replicas:
            self.api.update("replicasets", replace(active, replicas=dep.replicas))
