"""Disruption controller (pkg/controller/disruption/disruption.go).

Maintains PodDisruptionBudget status: for each PDB, the currently-healthy
count of pods matching its selector, the desired-healthy floor derived
from spec.minAvailable / spec.maxUnavailable, and
disruptionsAllowed = currentHealthy - desiredHealthy (floored at 0) —
the number preemption's PDB filter and the eviction subresource consult.

Expected-pod resolution: the reference walks the pod's controller scale
(getExpectedPodCount); here expected = matching non-terminal pods, which
is exact for absolute minAvailable and for maxUnavailable against the
live set (percentages resolve against that count — documented
divergence for mid-rollout percent budgets).

Healthy = Running phase on a node (the reference requires the Ready
condition; hollow kubelets report Running as their ready signal).
"""

from __future__ import annotations

import copy
import logging
import math
from typing import Optional

from ..api.selectors import match_label_selector
from ..api.types import Pod, PodDisruptionBudget

logger = logging.getLogger("kubernetes_tpu.controllers.disruption")


def _resolve(value, expected: int) -> int:
    """IntOrString: int, or 'N%' of expected rounded UP (the reference's
    GetValueFromIntOrPercent with roundUp=true for minAvailable)."""
    if isinstance(value, str) and value.endswith("%"):
        return math.ceil(expected * int(value[:-1]) / 100.0)
    return int(value)


class DisruptionController:
    def __init__(self, api, pdb_informer, pod_informer, queue):
        self.api = api
        self.pdb_informer = pdb_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.pdb_informer.add_event_handler(
            on_add=lambda p: self.queue.add(p.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_for_pod(p),
            on_update=lambda old, new: self._enqueue_for_pod(new),
            on_delete=lambda p: self._enqueue_for_pod(p),
        )

    def _enqueue_for_pod(self, pod: Pod) -> None:
        for pdb in self.pdb_informer.list():
            if pdb.namespace == pod.namespace and match_label_selector(pdb.selector, pod.labels):
                self.queue.add(pdb.key())

    def sync(self, key: str) -> None:
        self.sync_count += 1
        pdb: Optional[PodDisruptionBudget] = self.pdb_informer.get(key)
        if pdb is None:
            return
        matching = [
            p for p in self.pod_informer.list()
            if p.namespace == pdb.namespace and p.phase not in ("Succeeded", "Failed")
            and match_label_selector(pdb.selector, p.labels)
        ]
        expected = len(matching)
        healthy = sum(1 for p in matching if p.phase == "Running" and p.node_name)
        if pdb.min_available is not None:
            desired = _resolve(pdb.min_available, expected)
        elif pdb.max_unavailable is not None:
            desired = expected - _resolve(pdb.max_unavailable, expected)
        else:
            desired = expected  # no budget spec: nothing may be disrupted
        allowed = max(0, healthy - max(0, desired))
        if (pdb.current_healthy == healthy and pdb.desired_healthy == desired
                and pdb.expected_pods == expected and pdb.disruptions_allowed == allowed):
            return
        updated = copy.copy(pdb)
        updated.current_healthy = healthy
        updated.desired_healthy = max(0, desired)
        updated.expected_pods = expected
        updated.disruptions_allowed = allowed
        try:
            self.api.update("poddisruptionbudgets", updated)
        except KeyError:
            pass
