"""Control-plane controllers: the kube-controller-manager subset.

The reference starts ~35 reconcile loops from one binary
(cmd/kube-controller-manager/app/controllermanager.go:373
NewControllerInitializers). This package rebuilds the seventeen that
cover workload replication, node health, ownership, service membership,
namespace lifecycle, garbage collection, scheduled/finished workloads,
disruption budgets, quotas and autoscaling — as informer-driven
reconcilers over the (fake or HTTP) apiserver:

  * ReplicaSetController (pkg/controller/replicaset/replica_set.go):
    selector/owner-matched live pods vs .spec.replicas; creates missing
    replicas from the template, deletes surplus (pending-first victim
    order), replaces Failed pods.
  * DeploymentController (pkg/controller/deployment/): template-hash
    ReplicaSet generations.
  * JobController (pkg/controller/job/): parallelism/completions.
  * StatefulSetController (pkg/controller/statefulset/): stable ordinal
    identities, OrderedReady rollout, reverse-order scale-down.
  * DaemonSetController (pkg/controller/daemon/): one pod per eligible
    node, placed by the DEFAULT scheduler through a matchFields
    metadata.name affinity pin (ScheduleDaemonSetPods semantics).
  * EndpointsController (pkg/controller/endpoint/): Service selector →
    live backend membership.
  * GarbageCollectorController (pkg/controller/garbagecollector/):
    ownerReference cascade — deleting a Deployment deletes its
    ReplicaSets, whose deletes delete their pods.
  * NamespaceController (pkg/controller/namespace/): Terminating
    namespaces drain every namespaced object, then finalize.
  * NodeLifecycleController (pkg/controller/nodelifecycle/): node Ready
    condition → not-ready/unreachable taints (NoSchedule + NoExecute), and
    NoExecute eviction of pods without a matching toleration — which is
    what makes a "node death" flow end-to-end: evict → ReplicaSet refill →
    scheduler re-place.

Round-4 additions (pkg/controller counterparts in parentheses):

  * ReplicationControllerController (replication/) — the RC adapter over
    the ReplicaSet reconciler.
  * PodGCController (podgc/) — terminated-pod threshold sweep, orphaned
    pods on deleted nodes, unscheduled terminating pods.
  * TTLAfterFinishedController (ttlafterfinished/) — deletes finished
    Jobs after ttlSecondsAfterFinished.
  * CronJobController (cronjob/) — cron-schedule evaluation (utils/cron)
    spawning owned Jobs under Allow/Forbid/Replace policies.
  * DisruptionController (disruption/) — PDB status: currentHealthy /
    desiredHealthy / disruptionsAllowed, feeding preemption + eviction.
  * ServiceAccountController (serviceaccount/) — 'default' SA per
    namespace.
  * ResourceQuotaController (resourcequota/) — status.used reconciliation
    (enforcement lives in the admission chain).
  * HorizontalPodAutoscalerController (podautoscaler/) — v1 CPU-percent
    scaling from the PodMetrics kind.

Controllers share one informer set and drain per-controller workqueues
(client-go util/workqueue semantics: dedup-while-pending, re-add-after-get).
Clock-driven controllers (cron, TTL, GC, HPA) also hang off the manager's
resync ticker, the analogue of the reference's per-controller periods.
"""

from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollectorController
from .hpa import HorizontalPodAutoscalerController
from .job import JobController
from .manager import DEFAULT_CONTROLLERS, ControllerManager
from .namespace import NamespaceController
from .nodelifecycle import NodeLifecycleController, TAINT_NOT_READY
from .podgc import PodGCController
from .replicaset import ReplicaSetController
from .replication import ReplicationControllerController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .statefulset import StatefulSetController
from .ttlafterfinished import TTLAfterFinishedController
from .workqueue import WorkQueue

__all__ = [
    "ControllerManager",
    "CronJobController",
    "DEFAULT_CONTROLLERS",
    "DaemonSetController",
    "DeploymentController",
    "DisruptionController",
    "EndpointsController",
    "GarbageCollectorController",
    "HorizontalPodAutoscalerController",
    "JobController",
    "NamespaceController",
    "NodeLifecycleController",
    "PodGCController",
    "ReplicaSetController",
    "ReplicationControllerController",
    "ResourceQuotaController",
    "ServiceAccountController",
    "StatefulSetController",
    "TAINT_NOT_READY",
    "TTLAfterFinishedController",
    "WorkQueue",
]
