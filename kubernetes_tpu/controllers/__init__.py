"""Control-plane controllers: the kube-controller-manager subset.

The reference starts ~35 reconcile loops from one binary
(cmd/kube-controller-manager/app/controllermanager.go:373
NewControllerInitializers). This package rebuilds the two that close the
scheduling loop — workload replication and node health — as informer-driven
reconcilers over the fake apiserver:

  * ReplicaSetController (pkg/controller/replicaset/replica_set.go):
    selector/owner-matched live pods vs .spec.replicas; creates missing
    replicas from the template, deletes surplus (pending-first victim
    order), replaces Failed pods.
  * NodeLifecycleController (pkg/controller/nodelifecycle/): node Ready
    condition → not-ready/unreachable taints (NoSchedule + NoExecute), and
    NoExecute eviction of pods without a matching toleration — which is
    what makes a "node death" flow end-to-end: evict → ReplicaSet refill →
    scheduler re-place.

Controllers share one informer set and drain per-controller workqueues
(client-go util/workqueue semantics: dedup-while-pending, re-add-after-get).
"""

from .deployment import DeploymentController
from .job import JobController
from .manager import ControllerManager
from .nodelifecycle import NodeLifecycleController, TAINT_NOT_READY
from .replicaset import ReplicaSetController
from .workqueue import WorkQueue

__all__ = [
    "ControllerManager",
    "DeploymentController",
    "JobController",
    "NodeLifecycleController",
    "ReplicaSetController",
    "TAINT_NOT_READY",
    "WorkQueue",
]
