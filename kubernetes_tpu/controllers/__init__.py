"""Control-plane controllers: the kube-controller-manager subset.

The reference starts ~35 reconcile loops from one binary
(cmd/kube-controller-manager/app/controllermanager.go:373
NewControllerInitializers). This package rebuilds the nine that close the
scheduling loop — workload replication, node health, ownership, service
membership, and namespace lifecycle — as informer-driven reconcilers over
the (fake or HTTP) apiserver:

  * ReplicaSetController (pkg/controller/replicaset/replica_set.go):
    selector/owner-matched live pods vs .spec.replicas; creates missing
    replicas from the template, deletes surplus (pending-first victim
    order), replaces Failed pods.
  * DeploymentController (pkg/controller/deployment/): template-hash
    ReplicaSet generations.
  * JobController (pkg/controller/job/): parallelism/completions.
  * StatefulSetController (pkg/controller/statefulset/): stable ordinal
    identities, OrderedReady rollout, reverse-order scale-down.
  * DaemonSetController (pkg/controller/daemon/): one pod per eligible
    node, placed by the DEFAULT scheduler through a matchFields
    metadata.name affinity pin (ScheduleDaemonSetPods semantics).
  * EndpointsController (pkg/controller/endpoint/): Service selector →
    live backend membership.
  * GarbageCollectorController (pkg/controller/garbagecollector/):
    ownerReference cascade — deleting a Deployment deletes its
    ReplicaSets, whose deletes delete their pods.
  * NamespaceController (pkg/controller/namespace/): Terminating
    namespaces drain every namespaced object, then finalize.
  * NodeLifecycleController (pkg/controller/nodelifecycle/): node Ready
    condition → not-ready/unreachable taints (NoSchedule + NoExecute), and
    NoExecute eviction of pods without a matching toleration — which is
    what makes a "node death" flow end-to-end: evict → ReplicaSet refill →
    scheduler re-place.

Controllers share one informer set and drain per-controller workqueues
(client-go util/workqueue semantics: dedup-while-pending, re-add-after-get).
"""

from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .endpoints import EndpointsController
from .garbagecollector import GarbageCollectorController
from .job import JobController
from .manager import DEFAULT_CONTROLLERS, ControllerManager
from .namespace import NamespaceController
from .nodelifecycle import NodeLifecycleController, TAINT_NOT_READY
from .replicaset import ReplicaSetController
from .statefulset import StatefulSetController
from .workqueue import WorkQueue

__all__ = [
    "ControllerManager",
    "DEFAULT_CONTROLLERS",
    "DaemonSetController",
    "DeploymentController",
    "EndpointsController",
    "GarbageCollectorController",
    "JobController",
    "NamespaceController",
    "NodeLifecycleController",
    "ReplicaSetController",
    "StatefulSetController",
    "TAINT_NOT_READY",
    "WorkQueue",
]
