"""Endpoints controller: Service selector → live backend membership.

Reference: pkg/controller/endpoint/endpoints_controller.go syncService —
for each Service, the Endpoints object of the same name lists the READY
pods matched by the selector. Pod IPs are not modeled; membership is
recorded as pod keys (the scheduling-visible contract the SelectorSpread/
ServiceAntiAffinity priorities and the service listers consume)."""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import Endpoints, Pod, Service
from ..apiserver.store import ConflictError, NotFoundError

logger = logging.getLogger("kubernetes_tpu.controllers.endpoints")


def _selects(svc: Service, labels) -> bool:
    """Service.spec.selector is a plain map: every pair must match; an
    empty selector selects nothing (endpoints_controller.go skips
    selector-less services)."""
    return bool(svc.selector) and all(labels.get(k) == v for k, v in svc.selector.items())


class EndpointsController:
    def __init__(self, api, svc_informer, pod_informer, queue):
        self.api = api
        self.svc_informer = svc_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.svc_informer.add_event_handler(
            on_add=lambda s: self.queue.add(s.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda s: self.queue.add(s.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_matching(p),
            on_update=lambda old, new: self._enqueue_matching(new),
            on_delete=lambda p: self._enqueue_matching(p),
        )

    def _enqueue_matching(self, pod: Pod) -> None:
        for svc in self.svc_informer.list():
            if svc.namespace == pod.namespace and _selects(svc, pod.labels):
                self.queue.add(svc.key())

    def sync(self, key: str) -> None:
        self.sync_count += 1
        svc: Optional[Service] = self.svc_informer.get(key)
        if svc is None:
            # service gone → endpoints follow (syncService's delete branch)
            try:
                self.api.delete("endpoints", key)
            except KeyError:
                pass
            return
        addrs = sorted(
            p.key()
            for p in self.pod_informer.list()
            if p.namespace == svc.namespace
            and _selects(svc, p.labels)
            and p.node_name  # scheduled (ready-gate proxy)
            and p.phase not in ("Failed", "Succeeded")
        )
        ep = Endpoints(name=svc.name, namespace=svc.namespace, addresses=addrs)
        try:
            current = self.api.get("endpoints", ep.key())
            if current.addresses == addrs:
                return  # no-op update suppression (the controller's courtesy)
            self.api.update("endpoints", ep)
        except (KeyError, NotFoundError):
            try:
                self.api.create("endpoints", ep)
            except ConflictError:
                self.api.update("endpoints", ep)
