"""ServiceAccount controller (pkg/controller/serviceaccount/
serviceaccounts_controller.go): ensures every Active namespace carries
the 'default' ServiceAccount, recreating it if deleted. The reference
also recreates on SA-delete events; both triggers are wired."""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import Namespace, ServiceAccount
from ..apiserver.store import ConflictError

logger = logging.getLogger("kubernetes_tpu.controllers.serviceaccount")

MANAGED_NAMES = ("default",)


class ServiceAccountController:
    def __init__(self, api, namespace_informer, serviceaccount_informer, queue):
        self.api = api
        self.namespace_informer = namespace_informer
        self.serviceaccount_informer = serviceaccount_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.namespace_informer.add_event_handler(
            on_add=lambda ns: self.queue.add(ns.name),
            on_update=lambda old, new: self.queue.add(new.name),
        )
        self.serviceaccount_informer.add_event_handler(
            on_delete=lambda sa: self.queue.add(sa.namespace),
        )

    def sync(self, key: str) -> None:
        self.sync_count += 1
        ns: Optional[Namespace] = self.namespace_informer.get(key)
        if ns is None or ns.phase != "Active":
            return
        have = {sa.name for sa in self.serviceaccount_informer.list()
                if sa.namespace == key}
        for name in MANAGED_NAMES:
            if name not in have:
                try:
                    self.api.create("serviceaccounts", ServiceAccount(name=name, namespace=key))
                except ConflictError:
                    pass
