"""StatefulSet controller: stable ordinal identities, ordered rollout.

Reference: pkg/controller/statefulset/stateful_set.go +
stateful_set_control.go UpdateStatefulSet: pods are named
<set>-<ordinal>; scale-up creates ordinal i only once 0..i-1 are created
and Running (OrderedReady pod management), scale-down deletes the highest
ordinal first, one at a time. Identity is stable: a failed/evicted
ordinal is re-created with the SAME name (the re-created pod flows
through the scheduler again)."""

from __future__ import annotations

import logging
from typing import Dict, Optional

from ..api.types import Pod, StatefulSet, _new_uid
from .podowner import owned_by

logger = logging.getLogger("kubernetes_tpu.controllers.statefulset")


class StatefulSetController:
    def __init__(self, api, ss_informer, pod_informer, queue):
        self.api = api
        self.ss_informer = ss_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.ss_informer.add_event_handler(
            on_add=lambda s: self.queue.add(s.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda s: self.queue.add(s.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_owner(p),
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=lambda p: self._enqueue_owner(p),
        )

    def _enqueue_owner(self, pod: Pod) -> None:
        for ref in pod.owner_references:
            if ref.get("controller") and ref.get("kind") == "StatefulSet":
                self.queue.add(f"{pod.namespace}/{ref.get('name')}")
                return

    def sync(self, key: str) -> None:
        self.sync_count += 1
        ss: Optional[StatefulSet] = self.ss_informer.get(key)
        if ss is None:
            return  # cascade is the GC's job
        by_ordinal: Dict[int, Pod] = {}
        terminal: Dict[int, Pod] = {}  # Failed/Succeeded pods still holding a name
        for p in self.pod_informer.list():
            if not owned_by(p, ss.uid):
                continue
            ordinal = _ordinal_of(ss.name, p.name)
            if ordinal is None:
                continue
            if p.phase in ("Failed", "Succeeded"):
                terminal[ordinal] = p
            else:
                by_ordinal[ordinal] = p
        # scale-down first: highest ordinal, one per sync (OrderedReady)
        surplus = sorted((o for o in by_ordinal if o >= ss.replicas), reverse=True)
        if surplus:
            victim = by_ordinal[surplus[0]]
            try:
                self.api.delete("pods", victim.key())
            except KeyError:
                pass
            return
        # scale-up: the lowest missing ordinal, only if every lower ordinal
        # is Running (the ordered-readiness gate)
        for i in range(ss.replicas):
            p = by_ordinal.get(i)
            if p is None:
                dead = terminal.get(i)
                if dead is not None:
                    # the terminal pod still owns the ordinal NAME — it
                    # must be deleted before the identity can be reborn
                    # (stateful_set_control.go replaces failed pods by
                    # delete-then-recreate under the same name)
                    try:
                        self.api.delete("pods", dead.key())
                    except KeyError:
                        pass
                    return  # the delete event re-enqueues; create next sync
                self.api.create("pods", self._ordinal_pod(ss, i))
                return
            if p.phase != "Running":
                return  # wait for i to become Ready before i+1

    def _ordinal_pod(self, ss: StatefulSet, ordinal: int) -> Pod:
        t = ss.template or Pod()
        pod = t.with_node("")
        pod.name = f"{ss.name}-{ordinal}"
        pod.namespace = ss.namespace
        pod.uid = _new_uid()
        pod.phase = "Pending"
        import time as _time

        pod.creation_timestamp = _time.time()
        pod.labels = dict(t.labels)
        pod.owner_references = [
            {"uid": ss.uid, "controller": True, "kind": "StatefulSet", "name": ss.name}
        ]
        return pod


def _ordinal_of(set_name: str, pod_name: str) -> Optional[int]:
    prefix = set_name + "-"
    if not pod_name.startswith(prefix):
        return None
    try:
        return int(pod_name[len(prefix):])
    except ValueError:
        return None
