"""Shared helpers for pod-owning controllers (ReplicaSet, Job): child
ownership tests and child-pod construction, so the owner-ref shape, the
generated-name scheme, and the deletion rank evolve in ONE place
(controller_utils.go's ActivePods ordering + NewControllerRef)."""

from __future__ import annotations

import itertools
import time

from ..api.types import Pod, _new_uid

_suffix = itertools.count(1)


def owned_by(pod: Pod, owner_uid: str) -> bool:
    return any(
        ref.get("controller") and ref.get("uid") == owner_uid
        for ref in pod.owner_references
    )


def deletion_rank(pod: Pod):
    """getPodsToDelete's order: unassigned (pending) victims first, then
    oldest-first among assigned (controller_utils.go ActivePods)."""
    return (pod.node_name != "", pod.creation_timestamp)


def new_child_pod(template, owner_kind: str, owner_name: str, owner_uid: str,
                  namespace: str) -> Pod:
    t = template or Pod()
    pod = t.with_node("")  # clone (request memos stay valid: same containers)
    pod.name = f"{owner_name}-{next(_suffix):05d}"
    pod.namespace = namespace
    pod.uid = _new_uid()
    pod.phase = "Pending"
    pod.creation_timestamp = time.time()
    pod.labels = dict(t.labels)
    pod.owner_references = [
        {"uid": owner_uid, "controller": True, "kind": owner_kind,
         "name": owner_name}
    ]
    return pod
