"""ReplicaSet controller (pkg/controller/replicaset/replica_set.go).

Reconcile contract (syncReplicaSet → manageReplicas, replica_set.go:560):
live pods owned by the RS (ownerReference.controller uid match) or adopted
by selector (orphans — simplified adoption: counted, not patched) are
compared against .spec.replicas; the diff is closed by creating replicas
from the template (generated names, fresh uids, owner reference stamped)
or deleting surplus — unscheduled/pending pods first, mirroring
getPodsToDelete's rank (controller_utils.go ActivePods ordering). Failed
pods never count as live, so an evicted/failed replica is replaced on the
next sync — the loop the nodelifecycle controller's NoExecute eviction
feeds into.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api.selectors import match_label_selector
from ..api.types import Pod, ReplicaSet
from .podowner import deletion_rank, new_child_pod, owned_by

logger = logging.getLogger("kubernetes_tpu.controllers.replicaset")

# manageReplicas burst ceiling (replica_set.go burstReplicas)
BURST_REPLICAS = 500


def _adoptable(pod: Pod, rs: ReplicaSet) -> bool:
    """Orphan matched by the RS selector (ClaimPods semantics, counted
    without patching the owner ref)."""
    if any(r.get("controller") for r in pod.owner_references):
        return False
    return pod.namespace == rs.namespace and match_label_selector(rs.selector, pod.labels)


class ReplicaSetController:
    """One reconcile loop: replicasets + pods informers → workqueue →
    manageReplicas through the (fake) apiserver.

    OWNER_KIND parameterizes the ownerReference kind so the
    ReplicationController adapter (pkg/controller/replication wraps the
    same reconciler in the reference) can subclass with its own kind."""

    OWNER_KIND = "ReplicaSet"

    def __init__(self, api, rs_informer, pod_informer, queue):
        self.api = api
        self.rs_informer = rs_informer
        self.pod_informer = pod_informer
        self.queue = queue
        self.sync_count = 0  # observability for tests

    # -- event handlers (replica_set.go addPod/updatePod/deletePod) ---------

    def register(self) -> None:
        self.rs_informer.add_event_handler(
            on_add=lambda rs: self.queue.add(rs.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda rs: self.queue.add(rs.key()),
        )
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._enqueue_owner(p),
            on_update=lambda old, new: self._enqueue_owner(new),
            on_delete=lambda p: self._enqueue_owner(p),
        )

    def _enqueue_owner(self, pod: Pod) -> None:
        for ref in pod.owner_references:
            if ref.get("controller") and ref.get("kind") == self.OWNER_KIND:
                self.queue.add(f"{pod.namespace}/{ref.get('name')}")
                return
        # orphan: any RS whose selector matches may want it
        for rs in self.rs_informer.list():
            if _adoptable(pod, rs):
                self.queue.add(rs.key())

    # -- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        self.sync_count += 1
        rs: Optional[ReplicaSet] = self.rs_informer.get(key)
        if rs is None:
            return  # deleted; orphaned pods keep running (no GC here)
        live: List[Pod] = []
        for p in self.pod_informer.list():
            if p.phase in ("Failed", "Succeeded"):
                continue
            if owned_by(p, rs.uid) or _adoptable(p, rs):
                live.append(p)
        diff = rs.replicas - len(live)
        if diff > 0:
            for _ in range(min(diff, BURST_REPLICAS)):
                self.api.create("pods", self._new_replica(rs))
        elif diff < 0:
            # deletion order: pending (unscheduled) before running
            # (controller_utils.go ActivePods: unassigned < assigned)
            victims = sorted(live, key=deletion_rank)
            for p in victims[: min(-diff, BURST_REPLICAS)]:
                try:
                    self.api.delete("pods", p.key())
                except KeyError:
                    pass

    def _new_replica(self, rs: ReplicaSet) -> Pod:
        return new_child_pod(rs.template, self.OWNER_KIND, rs.name, rs.uid, rs.namespace)
