"""Horizontal pod autoscaler (pkg/controller/podautoscaler/horizontal.go).

Scales a target workload (Deployment / ReplicaSet / ReplicationController
/ StatefulSet) toward spec.targetCPUUtilizationPercentage using the
v1 algorithm (replica_calculator.go GetResourceReplicas):

  utilization = sum(usage) / sum(requests) over measured pods (percent)
  desired = ceil(usageRatio × measuredPodCount), clamped [min, max]

Multiplying by the MEASURED pod count (not scale.replicas) is what makes
the loop robust to informer lag: right after a scale-up, the target's
replica count is already higher while the new pod is not yet visible —
ratio × scale.replicas would compound the scale-up into an overshoot.

Metrics come from the PodMetrics kind (metrics.k8s.io analogue) that the
node runtime publishes. Missing-metrics conservatism follows
replica_calculator.go: when scaling UP, pods without metrics are assumed
to use 0 (so a just-created replica dampens further scale-up instead of
being invisible); when scaling DOWN, they are assumed at 100% of request.
A 10% tolerance band suppresses thrashy scaling (horizontal.go
tolerance)."""

from __future__ import annotations

import copy
import logging
import math
import time
from typing import Optional

from ..api.selectors import match_label_selector
from ..api.types import HorizontalPodAutoscaler, RESOURCE_CPU
from ..apiserver.store import ConflictError

logger = logging.getLogger("kubernetes_tpu.controllers.hpa")

TOLERANCE = 0.1  # horizontal.go defaultTolerance

_TARGET_KINDS = {
    "Deployment": "deployments",
    "ReplicaSet": "replicasets",
    "ReplicationController": "replicationcontrollers",
    "StatefulSet": "statefulsets",
}


class HorizontalPodAutoscalerController:
    def __init__(self, api, hpa_informer, pod_informer, podmetrics_informer, queue,
                 downscale_forbidden_s: float = 300.0,
                 upscale_forbidden_s: float = 180.0):
        self.api = api
        self.hpa_informer = hpa_informer
        self.pod_informer = pod_informer
        self.podmetrics_informer = podmetrics_informer
        self.queue = queue
        self.sync_count = 0
        self.scale_count = 0
        # horizontal.go shouldScale: a rescale is only allowed once the
        # forbidden window since lastScaleTime has passed (5m down / 3m up
        # defaults), so transient metric dips/spikes don't flap replicas
        self.downscale_forbidden_s = downscale_forbidden_s
        self.upscale_forbidden_s = upscale_forbidden_s

    def register(self) -> None:
        self.hpa_informer.add_event_handler(
            on_add=lambda h: self.queue.add(h.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )

    def resync_all(self) -> None:
        for h in self.hpa_informer.list():
            self.queue.add(h.key())

    def sync(self, key: str) -> None:
        self.sync_count += 1
        hpa: Optional[HorizontalPodAutoscaler] = self.hpa_informer.get(key)
        if hpa is None:
            return
        kind = _TARGET_KINDS.get(hpa.target_kind)
        if kind is None or hpa.target_cpu_utilization_pct <= 0:
            # the reference's API validation requires target >= 1; with no
            # validation webhook here, a zero target must not divide
            return
        try:
            target = self.api.get(kind, f"{hpa.namespace}/{hpa.target_name}")
        except KeyError:
            return
        current = target.replicas
        matching = [
            p for p in self.pod_informer.list()
            if p.namespace == hpa.namespace and p.phase not in ("Succeeded", "Failed")
            and match_label_selector(target.selector, p.labels)
        ]
        usage = requests = 0
        measured = 0  # pods with both a cpu request and a metrics sample
        missing_req = 0  # pods with a request but no metrics sample yet
        missing = 0
        for p in matching:
            req = p.resource_request().get(RESOURCE_CPU, 0)
            if req <= 0:
                continue
            m = self.podmetrics_informer.get(p.key())
            if m is None:
                missing += 1
                missing_req += req
                continue
            usage += m.cpu_milli
            requests += req
            measured += 1
        if requests <= 0 or current <= 0:
            return  # no usable metrics yet
        utilization = 100.0 * usage / requests
        ratio = utilization / hpa.target_cpu_utilization_pct
        count = measured
        if missing and abs(ratio - 1.0) > TOLERANCE:
            # replica_calculator.go: re-run with missing pods at 0 usage
            # (scale up) or full request (scale down); if the adjusted
            # ratio flips direction, hold steady
            if ratio > 1.0:
                adj = (100.0 * usage / (requests + missing_req)) / hpa.target_cpu_utilization_pct
                ratio = adj if adj > 1.0 else 1.0
            else:
                adj = (100.0 * (usage + missing_req) / (requests + missing_req)) \
                    / hpa.target_cpu_utilization_pct
                ratio = adj if adj < 1.0 else 1.0
            count = measured + missing
        desired = current if abs(ratio - 1.0) <= TOLERANCE else math.ceil(count * ratio)
        desired = max(hpa.min_replicas, min(hpa.max_replicas, desired))

        if desired != current and hpa.last_scale_time is not None:
            since = time.time() - hpa.last_scale_time
            window = (self.downscale_forbidden_s if desired < current
                      else self.upscale_forbidden_s)
            if since < window:
                # forbidden window: hold the scale but still publish status
                # (reconcileAutoscaler sets desiredReplicas = currentReplicas
                # when shouldScale is false, then writes status regardless)
                desired = current

        scaled_now = False
        if desired != current:
            scaled = copy.copy(target)
            scaled.replicas = desired
            try:
                self.api.update(kind, scaled)
                self.scale_count += 1
                scaled_now = True
            except (KeyError, ConflictError):
                return  # retried on the next tick

        st = copy.copy(self.hpa_informer.get(key) or hpa)
        if (st.current_replicas == current and st.desired_replicas == desired
                and st.current_cpu_utilization_pct == int(utilization)):
            return
        st.current_replicas = current
        st.desired_replicas = desired
        st.current_cpu_utilization_pct = int(utilization)
        if scaled_now:
            st.last_scale_time = time.time()
        try:
            self.api.update("horizontalpodautoscalers", st)
        except KeyError:
            pass
