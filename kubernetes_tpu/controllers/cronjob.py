"""CronJob controller (pkg/controller/cronjob/cronjob_controller.go).

The reference polls: syncAll() every 10s lists all CronJobs + Jobs and,
per CronJob, computes the unmet schedule times since lastScheduleTime
(getRecentUnmetScheduleTimes, utils.go) and starts a Job for the most
recent one, honoring concurrencyPolicy:

* Allow  — start regardless of running jobs
* Forbid — skip this cycle if an owned job is still active
* Replace — delete active owned jobs, then start

Job names are `{cronjob}-{scheduled-minute-epoch}` (getJobName), which
also dedupes: if the job for a scheduled time already exists, it is not
started twice. Owner references make the garbage collector cascade
cronjob deletion to its jobs (and through jobs to pods).
"""

from __future__ import annotations

import copy
import logging
import time
from typing import List, Optional

from ..api.types import CronJob, Job
from ..apiserver.store import ConflictError
from ..utils.cron import CronParseError, CronSchedule

logger = logging.getLogger("kubernetes_tpu.controllers.cronjob")


class CronJobController:
    def __init__(self, api, cronjob_informer, job_informer, queue):
        self.api = api
        self.cronjob_informer = cronjob_informer
        self.job_informer = job_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.cronjob_informer.add_event_handler(
            on_add=lambda cj: self.queue.add(cj.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
        )

    def resync_all(self) -> None:
        for cj in self.cronjob_informer.list():
            self.queue.add(cj.key())

    def _owned_jobs(self, cj: CronJob) -> List[Job]:
        return [
            j for j in self.job_informer.list()
            if any(r.get("uid") == cj.uid and r.get("controller")
                   for r in j.owner_references)
        ]

    def sync(self, key: str) -> None:
        self.sync_count += 1
        cj: Optional[CronJob] = self.cronjob_informer.get(key)
        if cj is None or cj.suspend or cj.job_template is None:
            return
        try:
            sched = CronSchedule(cj.schedule)
        except CronParseError:
            logger.warning("cronjob %s: unparseable schedule %r", key, cj.schedule)
            return
        now = time.time()
        # no lastScheduleTime yet: the earliest time we may fire for is the
        # CronJob's creation (getRecentUnmetScheduleTimes earliestTime =
        # sj.ObjectMeta.CreationTimestamp) — never a boundary that predates
        # the object
        last = cj.last_schedule_time if cj.last_schedule_time is not None \
            else cj.creation_timestamp
        unmet = sched.unmet_since(last, now)
        if not unmet:
            if cj.last_schedule_time is not None and sched.next_after(last) is not None \
                    and sched.next_after(last) <= now:
                # unmet_since gave up: >100 missed starts (long downtime /
                # clock skew). The reference sticks with a warning event;
                # we self-heal by advancing lastScheduleTime so the next
                # due time schedules normally (documented divergence).
                logger.warning("cronjob %s: too many missed start times; "
                               "advancing lastScheduleTime", key)
                healed = copy.copy(cj)
                healed.last_schedule_time = now
                try:
                    self.api.update("cronjobs", healed)
                except KeyError:
                    pass
            return
        scheduled = unmet[-1]  # most recent only (reference: startJob for the last)
        job_name = f"{cj.name}-{int(scheduled // 60)}"

        active = [j for j in self._owned_jobs(cj)
                  if j.completion_time is None]
        if cj.concurrency_policy == "Forbid" and active:
            return
        if cj.concurrency_policy == "Replace":
            for j in active:
                if j.name == job_name:
                    # already the job for this scheduled time (informer lag
                    # can replay the same unmet time before the status write
                    # lands) — deleting it would free the name and defeat
                    # the ConflictError dedupe below, churning the job
                    continue
                try:
                    self.api.delete("jobs", j.key())
                except KeyError:
                    pass

        job = copy.deepcopy(cj.job_template)
        job.name = job_name
        job.namespace = cj.namespace
        job.resource_version = ""
        job.owner_references = [
            {"uid": cj.uid, "controller": True, "kind": "CronJob", "name": cj.name}
        ]
        from ..api.types import _new_uid

        job.uid = _new_uid()
        try:
            self.api.create("jobs", job)
        except ConflictError:
            pass  # this scheduled time already started (dedupe by name)

        updated = copy.copy(self.cronjob_informer.get(key) or cj)
        updated.last_schedule_time = scheduled
        try:
            self.api.update("cronjobs", updated)
        except KeyError:
            pass
