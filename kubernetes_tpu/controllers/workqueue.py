"""client-go util/workqueue (Type) semantics, the subset controllers use:

  * add(item) — enqueue; a key already queued is deduped; a key currently
    being PROCESSED is marked dirty and re-queued when done() is called
    (workqueue/queue.go Add/Get/Done).
  * get() — block for the next key (None after shutdown).
  * done(item) — processing finished; re-queue if it went dirty meanwhile.

Rate limiting is reduced to a bounded retry counter the caller manages
(controllers here re-add on reconcile error up to a few times); the
reference's token-bucket delays exist to protect a remote apiserver that
this in-process store does not need.
"""

from __future__ import annotations

from collections import deque
from typing import Hashable, Optional

from ..analysis.lockorder import audited_condition


class WorkQueue:
    def __init__(self):
        self._cond = audited_condition("workqueue")
        self._queue: deque = deque()  # ktpu: guarded-by(self._cond)
        self._queued: set = set()  # ktpu: guarded-by(self._cond)
        self._processing: set = set()  # ktpu: guarded-by(self._cond)
        self._dirty: set = set()  # ktpu: guarded-by(self._cond)
        self._shutdown = False  # ktpu: guarded-by(self._cond)

    def add(self, item: Hashable) -> None:
        with self._cond:
            if self._shutdown:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            if item in self._queued:
                return
            self._queued.add(item)
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        with self._cond:
            while not self._queue and not self._shutdown:
                if not self._cond.wait(timeout=timeout):
                    return None
            if self._shutdown and not self._queue:
                return None
            item = self._queue.popleft()
            self._queued.discard(item)
            self._processing.add(item)
            return item

    def done(self, item: Hashable) -> None:
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._dirty.discard(item)
                if item not in self._queued:
                    self._queued.add(item)
                    self._queue.append(item)
                    self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
