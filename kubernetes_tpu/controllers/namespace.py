"""Namespace lifecycle controller: Terminating namespaces drain.

Reference: pkg/controller/namespace/deletion/
namespaced_resources_deleter.go — a namespace marked Terminating has
every namespaced resource deleted, then the namespace itself is removed
(finalization). The store has no finalizers; the observable contract is
the same: set phase=Terminating (or delete the Namespace object) and the
namespace's contents go away."""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import Namespace

logger = logging.getLogger("kubernetes_tpu.controllers.namespace")

# every namespaced kind the store may hold
NAMESPACED_KINDS = (
    "pods", "replicasets", "deployments", "jobs", "statefulsets",
    "daemonsets", "services", "endpoints", "events",
    "replicationcontrollers", "cronjobs", "poddisruptionbudgets",
    "serviceaccounts", "resourcequotas", "limitranges",
    "horizontalpodautoscalers", "podmetrics",
)


class NamespaceController:
    def __init__(self, api, ns_informer, queue):
        self.api = api
        self.ns_informer = ns_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.ns_informer.add_event_handler(
            on_add=lambda ns: self.queue.add(ns.key()),
            on_update=lambda old, new: self.queue.add(new.key()),
            on_delete=lambda ns: self.queue.add(ns.key()),
        )

    def sync(self, key: str) -> None:
        self.sync_count += 1
        ns: Optional[Namespace] = self.ns_informer.get(key)
        if ns is not None and ns.phase != "Terminating":
            return
        # Terminating OR deleted outright: drain the namespace's contents
        self._drain(key)
        if ns is not None:
            # finalize: contents gone → the namespace object goes away
            try:
                self.api.delete("namespaces", key)
            except KeyError:
                pass

    def _drain(self, namespace: str) -> int:
        removed = 0
        for kind in NAMESPACED_KINDS:
            try:
                items, _ = self.api.list(kind)
            except Exception:
                continue
            for obj in items:
                if getattr(obj, "namespace", None) != namespace:
                    continue
                try:
                    self.api.delete(kind, obj.key())
                    removed += 1
                except KeyError:
                    pass
        if removed:
            logger.info("namespace %s: drained %d objects", namespace, removed)
        return removed
