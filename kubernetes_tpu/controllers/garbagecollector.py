"""Garbage collector: ownerReference cascade deletion.

Reference: pkg/controller/garbagecollector/garbagecollector.go (:83
NewGarbageCollector): a dependency graph over ownerReferences; deleting an
owner enqueues its dependents, and attemptToDeleteItem removes any object
whose CONTROLLER owner no longer exists (by uid). This implementation
keeps the same observable contract with a flat scan instead of the graph:

* owner delete event → enqueue every dependent kind for an orphan sweep;
* sweep: an object whose controller ownerReference names a uid that no
  longer exists in the owner kind's store is deleted (foreground-style
  cascade: deleting a Deployment deletes its ReplicaSets, whose deletes
  re-enqueue and delete their Pods).

Orphan-intent (ownerReference.blockOwnerDeletion / orphan finalizers) is
out of scope — cascade is the default path the reference takes for the
workload kinds modeled here.
"""

from __future__ import annotations

import logging
from typing import Dict, List

logger = logging.getLogger("kubernetes_tpu.controllers.garbagecollector")

# dependent kind → owner kinds whose disappearance orphans it
DEPENDENTS: Dict[str, List[str]] = {
    "pods": ["replicasets", "jobs", "statefulsets", "daemonsets"],
    "replicasets": ["deployments"],
    "endpoints": ["services"],
}

_SWEEP = "__sweep__"

# store kind → wire Kind (ownerReference.kind values)
_OWNER_WIRE_KIND = {
    "replicasets": "ReplicaSet",
    "jobs": "Job",
    "statefulsets": "StatefulSet",
    "daemonsets": "DaemonSet",
    "deployments": "Deployment",
    "services": "Service",
}


class GarbageCollectorController:
    def __init__(self, api, informers: Dict[str, object], queue):
        """`informers` must cover every kind named in DEPENDENTS (owners and
        dependents); missing kinds are skipped."""
        self.api = api
        self.informers = informers
        self.queue = queue
        self.deleted = 0  # observability for tests

    def register(self) -> None:
        owner_kinds = {k for owners in DEPENDENTS.values() for k in owners}
        for kind in owner_kinds:
            inf = self.informers.get(kind)
            if inf is None:
                continue
            inf.add_event_handler(
                on_delete=lambda obj, _k=kind: self.queue.add(_SWEEP)
            )
        # dependents arriving AFTER their owner died must not linger
        for kind in DEPENDENTS:
            inf = self.informers.get(kind)
            if inf is None:
                continue
            inf.add_event_handler(on_add=lambda obj: self.queue.add(_SWEEP))

    def sync(self, key: str) -> None:
        self.sweep()

    def sweep(self) -> int:
        """One orphan sweep over every dependent kind. Returns deletions."""
        removed = 0
        for kind, owner_kinds in DEPENDENTS.items():
            inf = self.informers.get(kind)
            if inf is None:
                continue
            live_uids = set()
            wire_kinds = {_OWNER_WIRE_KIND[k] for k in owner_kinds if k in _OWNER_WIRE_KIND}
            for ok in owner_kinds:
                oinf = self.informers.get(ok)
                if oinf is None:
                    continue
                for owner in oinf.list():
                    uid = getattr(owner, "uid", None)
                    if uid:
                        live_uids.add(uid)
            for obj in inf.list():
                refs = getattr(obj, "owner_references", None)
                if refs is None:
                    # endpoints: implicit ownership by same-named service
                    if kind == "endpoints":
                        svc_inf = self.informers.get("services")
                        if svc_inf is not None and svc_inf.get(obj.key()) is None:
                            removed += self._delete(kind, obj)
                    continue
                ctrl = next((r for r in refs if r.get("controller")), None)
                if ctrl is None:
                    continue
                if ctrl.get("kind") not in wire_kinds:
                    continue  # owned by a kind we don't track: leave it
                if ctrl.get("uid") not in live_uids:
                    removed += self._delete(kind, obj)
        self.deleted += removed
        return removed

    def _delete(self, kind: str, obj) -> int:
        try:
            self.api.delete(kind, obj.key())
            logger.info("gc: deleted orphaned %s %s", kind, obj.key())
            return 1
        except KeyError:
            return 0
