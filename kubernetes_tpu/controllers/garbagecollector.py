"""Garbage collector: ownerReference cascade deletion.

Reference: pkg/controller/garbagecollector/garbagecollector.go (:83
NewGarbageCollector): a dependency graph over ownerReferences; deleting an
owner enqueues its dependents, and attemptToDeleteItem removes any object
whose CONTROLLER owner no longer exists (by uid). This implementation
keeps the same observable contract with a flat scan instead of the graph:

* owner delete event → enqueue every dependent kind for an orphan sweep;
* sweep: an object whose controller ownerReference names a uid that no
  longer exists in the owner kind's store is deleted (foreground-style
  cascade: deleting a Deployment deletes its ReplicaSets, whose deletes
  re-enqueue and delete their Pods).

Orphan-intent (ownerReference.blockOwnerDeletion / orphan finalizers) is
out of scope — cascade is the default path the reference takes for the
workload kinds modeled here.
"""

from __future__ import annotations

import logging
from typing import Dict, List

logger = logging.getLogger("kubernetes_tpu.controllers.garbagecollector")

# dependent kind → owner kinds whose disappearance orphans it
DEPENDENTS: Dict[str, List[str]] = {
    "pods": ["replicasets", "jobs", "statefulsets", "daemonsets",
             "replicationcontrollers"],
    "replicasets": ["deployments"],
    "jobs": ["cronjobs"],
    "endpoints": ["services"],
}

_SWEEP = "__sweep__"

# store kind → wire Kind (ownerReference.kind values)
_OWNER_WIRE_KIND = {
    "replicasets": "ReplicaSet",
    "jobs": "Job",
    "statefulsets": "StatefulSet",
    "daemonsets": "DaemonSet",
    "deployments": "Deployment",
    "services": "Service",
    "replicationcontrollers": "ReplicationController",
    "cronjobs": "CronJob",
}


class GarbageCollectorController:
    def __init__(self, api, informers: Dict[str, object], queue):
        """`informers` must cover every kind named in DEPENDENTS (owners and
        dependents); missing kinds are skipped."""
        self.api = api
        self.informers = informers
        self.queue = queue
        self.deleted = 0  # observability for tests

    def register(self) -> None:
        owner_kinds = {k for owners in DEPENDENTS.values() for k in owners}
        for kind in owner_kinds:
            inf = self.informers.get(kind)
            if inf is None:
                continue
            inf.add_event_handler(
                on_delete=lambda obj, _k=kind: self.queue.add(_SWEEP)
            )
        # dependents arriving AFTER their owner died must not linger —
        # but a full-cluster sweep per pod ADDED would be O(cluster) per
        # event under bench churn; enqueue a targeted single-object check
        # instead (the graph-based reference enqueues exactly the one
        # dependent too, garbagecollector.go attemptToDeleteItem)
        for kind in DEPENDENTS:
            inf = self.informers.get(kind)
            if inf is None:
                continue
            inf.add_event_handler(
                on_add=lambda obj, _k=kind: self.queue.add((_k, obj.key()))
            )

    def sync(self, key) -> None:
        if key == _SWEEP:
            self.sweep()
        else:
            self.check_one(*key)

    def check_one(self, kind: str, obj_key: str) -> None:
        """Targeted attemptToDeleteItem: is THIS object's controller owner
        still alive? (No cluster scan.)"""
        inf = self.informers.get(kind)
        if inf is None:
            return
        obj = inf.get(obj_key)
        if obj is None:
            return
        refs = getattr(obj, "owner_references", None)
        if not refs:
            if kind == "endpoints":
                svc_inf = self.informers.get("services")
                if svc_inf is not None and svc_inf.get(obj.key()) is None:
                    self.deleted += self._delete(kind, obj)
            return
        ctrl = next((r for r in refs if r.get("controller")), None)
        if ctrl is None:
            return
        for ok in DEPENDENTS.get(kind, ()):
            if _OWNER_WIRE_KIND.get(ok) != ctrl.get("kind"):
                continue
            oinf = self.informers.get(ok)
            if oinf is None:
                return
            if not any(getattr(o, "uid", None) == ctrl.get("uid") for o in oinf.list()):
                # informer caches can lag; confirm with a live owner get
                # before the destructive delete (same discipline as podgc)
                owner_key = f"{getattr(obj, 'namespace', 'default')}/{ctrl.get('name')}"
                try:
                    live = self.api.get(ok, owner_key)
                    if getattr(live, "uid", None) == ctrl.get("uid"):
                        return  # owner exists; cache was behind
                except KeyError:
                    pass
                self.deleted += self._delete(kind, obj)
            return

    def sweep(self) -> int:
        """One orphan sweep over every dependent kind. Returns deletions."""
        removed = 0
        for kind, owner_kinds in DEPENDENTS.items():
            inf = self.informers.get(kind)
            if inf is None:
                continue
            live_uids = set()
            wire_kinds = {_OWNER_WIRE_KIND[k] for k in owner_kinds if k in _OWNER_WIRE_KIND}
            for ok in owner_kinds:
                oinf = self.informers.get(ok)
                if oinf is None:
                    continue
                for owner in oinf.list():
                    uid = getattr(owner, "uid", None)
                    if uid:
                        live_uids.add(uid)
            for obj in inf.list():
                refs = getattr(obj, "owner_references", None)
                if refs is None:
                    # endpoints: implicit ownership by same-named service
                    if kind == "endpoints":
                        svc_inf = self.informers.get("services")
                        if svc_inf is not None and svc_inf.get(obj.key()) is None:
                            removed += self._delete(kind, obj)
                    continue
                ctrl = next((r for r in refs if r.get("controller")), None)
                if ctrl is None:
                    continue
                if ctrl.get("kind") not in wire_kinds:
                    continue  # owned by a kind we don't track: leave it
                if ctrl.get("uid") not in live_uids:
                    removed += self._delete(kind, obj)
        self.deleted += removed
        return removed

    def _delete(self, kind: str, obj) -> int:
        try:
            self.api.delete(kind, obj.key())
            logger.info("gc: deleted orphaned %s %s", kind, obj.key())
            return 1
        except KeyError:
            return 0
