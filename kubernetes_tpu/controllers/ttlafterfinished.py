"""TTL-after-finished controller
(pkg/controller/ttlafterfinished/ttlafterfinished_controller.go, alpha
behind the TTLAfterFinished gate in this reference era).

Deletes a Job `ttlSecondsAfterFinished` seconds after it finishes
(status.completionTime set by the job controller). Deletion cascades to
the Job's pods through the garbage collector (ownerReferences). Jobs
whose TTL has not expired yet are retried on the resync tick (the
reference uses a delaying workqueue; the manager tick is our clock).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ..api.types import Job

logger = logging.getLogger("kubernetes_tpu.controllers.ttlafterfinished")


class TTLAfterFinishedController:
    def __init__(self, api, job_informer, queue):
        self.api = api
        self.job_informer = job_informer
        self.queue = queue
        self.sync_count = 0

    def register(self) -> None:
        self.job_informer.add_event_handler(
            on_add=lambda j: self._maybe_enqueue(j),
            on_update=lambda old, new: self._maybe_enqueue(new),
        )

    def _maybe_enqueue(self, job: Job) -> None:
        if job.ttl_seconds_after_finished is not None and job.completion_time is not None:
            self.queue.add(job.key())

    def resync_all(self) -> None:
        for j in self.job_informer.list():
            self._maybe_enqueue(j)

    def sync(self, key: str) -> None:
        self.sync_count += 1
        job: Optional[Job] = self.job_informer.get(key)
        if job is None or job.ttl_seconds_after_finished is None:
            return
        if job.completion_time is None:
            return  # not finished yet
        if time.time() < job.completion_time + job.ttl_seconds_after_finished:
            return  # not expired; the next tick re-enqueues
        logger.info("ttlafterfinished: deleting job %s", key)
        try:
            self.api.delete("jobs", key)
        except KeyError:
            pass
