"""Scheduling-relevant API types.

A from-scratch, typed model of the subset of `k8s.io/api/core/v1` that the
scheduler reads (reference inventory: SURVEY.md section 2.1; field usage drawn
from pkg/scheduler/algorithm/predicates/predicates.go and
pkg/scheduler/nodeinfo/node_info.go). Full v1 objects round-trip through
`from_k8s` / `to_k8s` so the extender server and the fake apiserver can speak
wire-format JSON.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .quantity import Quantity, parse_quantity

# Resource names the scheduler treats as first-class
# (reference: predicates.go:854 PodFitsResources checks cpu/memory/ephemeral-storage
# plus arbitrary scalar resources).
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_EPHEMERAL_STORAGE = "ephemeral-storage"
RESOURCE_PODS = "pods"

# Taint effects (k8s.io/api/core/v1/types.go).
TAINT_NO_SCHEDULE = "NoSchedule"
TAINT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
TAINT_NO_EXECUTE = "NoExecute"

# Node taint applied for .spec.unschedulable (scheduler api TaintNodeUnschedulable).
TAINT_NODE_UNSCHEDULABLE = "node.kubernetes.io/unschedulable"

# TopologySpreadConstraint.whenUnsatisfiable values.
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

# Default priority when pod.Spec.Priority is nil (podutil.GetPodPriority).
DEFAULT_POD_PRIORITY = 0

_uid_counter = itertools.count(1)


def _new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ContainerPort:
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class Container:
    name: str = ""
    image: str = ""
    ports: List[ContainerPort] = field(default_factory=list)
    requests: Dict[str, Quantity] = field(default_factory=dict)
    limits: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = TAINT_NO_SCHEDULE


@dataclass
class Toleration:
    key: str = ""  # empty key with Exists matches all taints
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    # NoExecute grace: how long the pod stays on a tainted node before
    # eviction (None = forever). Set to 300 by the DefaultTolerationSeconds
    # admission plugin; honored by the nodelifecycle controller.
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: Taint) -> bool:
        """v1helper.TolerationsTolerateTaint semantics
        (staging/src/k8s.io/api/core/v1/toleration.go ToleratesTaint)."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator in ("", "Equal"):
            return self.value == taint.value
        if self.operator == "Exists":
            return True
        return False


def tolerations_tolerate_taint(tolerations: List[Toleration], taint: Taint) -> bool:
    return any(t.tolerates(taint) for t in tolerations)


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)
    match_fields: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class NodeSelector:
    node_selector_terms: List[NodeSelectorTerm] = field(default_factory=list)


@dataclass
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass
class NodeAffinity:
    required: Optional[NodeSelector] = None
    preferred: List[PreferredSchedulingTerm] = field(default_factory=list)


@dataclass
class LabelSelectorRequirement:
    key: str
    operator: str  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    """metav1.LabelSelector. None (absence) matches nothing; an empty selector
    matches everything (metav1.LabelSelectorAsSelector semantics)."""

    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    label_selector: Optional[LabelSelector] = None
    namespaces: List[str] = field(default_factory=list)
    topology_key: str = ""


@dataclass
class WeightedPodAffinityTerm:
    weight: int
    pod_affinity_term: PodAffinityTerm = field(default_factory=PodAffinityTerm)


@dataclass
class PodAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class PodAntiAffinity:
    required: List[PodAffinityTerm] = field(default_factory=list)
    preferred: List[WeightedPodAffinityTerm] = field(default_factory=list)


@dataclass
class Affinity:
    node_affinity: Optional[NodeAffinity] = None
    pod_affinity: Optional[PodAffinity] = None
    pod_anti_affinity: Optional[PodAntiAffinity] = None


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None


@dataclass
class Volume:
    """v1.Volume — the scheduling-visible sources (predicates.go volume
    predicates read exactly these): PVC references, the attachable in-tree
    disks (GCE PD / AWS EBS / Azure Disk), and the NoDiskConflict sources
    (GCE PD, AWS EBS, RBD, ISCSI). Everything else (emptyDir, configMap,
    ...) is scheduling-neutral and represented only by `name`."""

    name: str = ""
    pvc_claim_name: str = ""  # persistentVolumeClaim.claimName
    gce_pd_name: str = ""
    gce_pd_read_only: bool = False
    aws_volume_id: str = ""
    aws_read_only: bool = False
    azure_disk_name: str = ""
    rbd_pool: str = ""
    rbd_image: str = ""
    rbd_monitors: Tuple[str, ...] = ()
    rbd_read_only: bool = False
    iscsi_target_portal: str = ""
    iscsi_iqn: str = ""
    iscsi_lun: int = 0
    iscsi_read_only: bool = False


@dataclass
class Pod:
    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: str = ""
    creation_timestamp: float = field(default_factory=time.time)
    deletion_timestamp: Optional[float] = None
    owner_references: List[Dict[str, Any]] = field(default_factory=list)

    # spec
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    overhead: Dict[str, Quantity] = field(default_factory=dict)
    priority: Optional[int] = None
    priority_class_name: str = ""
    topology_spread_constraints: List[TopologySpreadConstraint] = field(default_factory=list)
    scheduler_name: str = "default-scheduler"
    host_network: bool = False
    volumes: List[Volume] = field(default_factory=list)

    # status
    phase: str = "Pending"
    nominated_node_name: str = ""
    conditions: List[Dict[str, Any]] = field(default_factory=list)

    def key(self) -> str:
        # memoized: called several times per pod per batch across the
        # state machine (assume/finish/forget), the queue, and the
        # oracle's remove-scan; with_node clones carry it. The memo is
        # VALIDATED against name/namespace identity — the controllers
        # clone a template and then rename it (new_child_pod,
        # StatefulSet._ordinal_pod), so a blind cache would pin every
        # child to the template's identity.
        m = self.__dict__.get("_key_memo")
        if m is not None and m[0] is self.namespace and m[1] is self.name:
            return m[2]
        k = f"{self.namespace}/{self.name}"
        self.__dict__["_key_memo"] = (self.namespace, self.name, k)
        return k

    def with_node(self, node_name: str) -> "Pod":
        """Shallow clone bound to a node — the assume-path equivalent of
        dataclasses.replace(pod, node_name=...), but ~20x cheaper (replace
        re-runs __init__ over every field; the commit loop pays it once
        per pod) and it carries the resource-request memo along."""
        clone = object.__new__(Pod)
        clone.__dict__.update(self.__dict__)
        clone.node_name = node_name
        return clone

    def get_priority(self) -> int:
        """podutil.GetPodPriority: nil priority -> 0."""
        return self.priority if self.priority is not None else DEFAULT_POD_PRIORITY

    def resource_request(self) -> Dict[str, int]:
        """predicates.GetResourceRequest semantics (predicates.go:~800-845):
        max(sum over containers, max over init containers) + overhead.
        cpu is millicores, memory/ephemeral-storage bytes, scalar resources
        in their own units (milli for hugepages-safety we use value()).

        Memoized after first call (the oracle evaluates it once per
        candidate NODE): callers must treat the returned dict as
        read-only, and the pod spec must not change after scheduling
        first sees it (updates arrive as new Pod objects)."""
        cached = getattr(self, "_req_cache", None)
        if cached is not None:
            return cached
        total: Dict[str, int] = {}
        for c in self.containers:
            for name, q in c.requests.items():
                total[name] = total.get(name, 0) + _request_value(name, q)
        for ic in self.init_containers:
            for name, q in ic.requests.items():
                v = _request_value(name, q)
                if v > total.get(name, 0):
                    total[name] = v
        for name, q in self.overhead.items():
            total[name] = total.get(name, 0) + _request_value(name, q)
        # ktpu: allow(KTPU006) idempotent memo on an effectively-immutable
        # pod: concurrent writers compute the identical dict (the Pod.key()
        # memo precedent) — last-write-wins is a benign race by design
        self._req_cache = total
        return total

    def host_ports(self) -> List[Tuple[str, str, int]]:
        """(protocol, hostIP, hostPort) triples with hostPort != 0
        (nodeinfo usedPorts representation, node_info.go HostPortInfo).
        Memoized (read per commit-loop recheck decision); treat the
        returned list as read-only. with_node clones carry the memo."""
        memo = self.__dict__.get("_host_ports_memo")
        if memo is not None:
            return memo
        out = []
        for c in self.containers:
            for p in c.ports:
                if p.host_port:
                    out.append((p.protocol or "TCP", p.host_ip or "0.0.0.0", p.host_port))
        self.__dict__["_host_ports_memo"] = out
        return out


@dataclass
class ContainerImage:
    names: List[str] = field(default_factory=list)
    size_bytes: int = 0


@dataclass
class Node:
    name: str = ""
    uid: str = field(default_factory=_new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    resource_version: str = ""

    # spec
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)

    # status
    capacity: Dict[str, Quantity] = field(default_factory=dict)
    allocatable: Dict[str, Quantity] = field(default_factory=dict)
    images: List[ContainerImage] = field(default_factory=list)
    conditions: List[Dict[str, Any]] = field(default_factory=list)

    def allocatable_int(self) -> Dict[str, int]:
        """Allocatable in scheduler units (cpu -> millicores, rest -> value).
        Memoized — the oracle reads it once per feasibility check and node
        allocatable is status the informer replaces wholesale (new Node
        object), never mutates. Treat the returned dict as read-only."""
        memo = self.__dict__.get("_alloc_int_memo")
        if memo is not None:
            return memo
        out = {}
        for name, q in self.allocatable.items():
            out[name] = _request_value(name, q)
        self.__dict__["_alloc_int_memo"] = out
        return out


@dataclass
class PodDisruptionBudget:
    """policy/v1beta1 PodDisruptionBudget. The scheduler consults
    status.disruptionsAllowed in preemption's PDB filter
    (filterPodsWithPDBViolation, core/generic_scheduler.go:1055); the
    disruption controller (pkg/controller/disruption/disruption.go)
    computes that status from spec.minAvailable / spec.maxUnavailable
    against the currently-healthy matching pods."""

    name: str = ""
    namespace: str = "default"
    selector: Optional[LabelSelector] = None
    resource_version: str = ""
    # spec — int, or a "N%" string resolved against the expected pod count
    min_available: Optional[Any] = None
    max_unavailable: Optional[Any] = None
    # status (disruption.go updatePdbStatus)
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass (pkg/apis/scheduling/types.go):
    name → integer priority, resolved into pod.spec.priority by the
    Priority admission plugin (plugin/pkg/admission/priority/admission.go)
    at pod-create time. Cluster-scoped."""

    name: str = ""
    value: int = 0
    global_default: bool = False
    description: str = ""
    resource_version: str = ""

    def key(self) -> str:
        return self.name


# scheduling/types.go system classes (created by the apiserver's
# PostStartHook in the reference; seeded by install_system_priority_classes)
SYSTEM_CLUSTER_CRITICAL = "system-cluster-critical"
SYSTEM_NODE_CRITICAL = "system-node-critical"
SYSTEM_CRITICAL_PRIORITY = 2_000_000_000
SYSTEM_PRIORITY_CLASSES = {
    SYSTEM_CLUSTER_CRITICAL: SYSTEM_CRITICAL_PRIORITY,
    SYSTEM_NODE_CRITICAL: SYSTEM_CRITICAL_PRIORITY + 1000,
}


def priorityclass_from_k8s(obj: dict) -> PriorityClass:
    meta = obj.get("metadata") or {}
    return PriorityClass(
        name=meta.get("name", ""),
        value=int(obj.get("value", 0)),
        global_default=bool(obj.get("globalDefault", False)),
        description=obj.get("description", ""),
        resource_version=str(meta.get("resourceVersion", "")),
    )


def priorityclass_to_k8s(pc: PriorityClass) -> dict:
    out = {
        "apiVersion": "scheduling.k8s.io/v1",
        "kind": "PriorityClass",
        "metadata": {"name": pc.name},
        "value": pc.value,
        "globalDefault": pc.global_default,
    }
    if pc.description:
        out["description"] = pc.description
    if pc.resource_version:
        out["metadata"]["resourceVersion"] = pc.resource_version
    return out


@dataclass
class Service:
    """core/v1 Service — the scheduling-visible subset: the label selector
    that groups pods, consumed by the ServiceAffinity custom predicate
    (predicates.go:1051) and the ServiceAntiAffinity / SelectorSpread
    priorities (selector_spreading.go)."""

    name: str = ""
    namespace: str = "default"
    selector: Dict[str, str] = field(default_factory=dict)
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ReplicaSet:
    """apps/v1 ReplicaSet — the controller-manager subset: desired replica
    count + selector + pod template (pkg/apis/apps/types.go ReplicaSetSpec;
    reconciled by pkg/controller/replicaset/replica_set.go syncReplicaSet).
    The template is a Pod whose name/uid are ignored (each replica gets a
    generated name and fresh uid)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None
    # controller ownership (a Deployment's uid), like Pod.owner_references
    owner_references: List[Dict[str, Any]] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def service_from_k8s(obj: dict) -> Service:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    return Service(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        selector=dict(spec.get("selector") or {}),
    )


def service_to_k8s(svc: Service) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": svc.name, "namespace": svc.namespace},
        "spec": {"selector": dict(svc.selector)},
    }


@dataclass
class Endpoints:
    """core/v1 Endpoints — the Service's live backend set, reconciled from
    the Service selector by the endpoints controller
    (pkg/controller/endpoint/endpoints_controller.go syncService).
    Addresses here are pod identities (pod IPs are not modeled; the
    scheduling-visible contract is membership)."""

    name: str = ""
    namespace: str = "default"
    addresses: List[str] = field(default_factory=list)  # pod keys, sorted
    resource_version: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def endpoints_from_k8s(obj: dict) -> Endpoints:
    meta = obj.get("metadata") or {}
    subsets = obj.get("subsets") or []
    addrs = []
    for s in subsets:
        for a in s.get("addresses") or []:
            ref = a.get("targetRef") or {}
            if ref.get("name"):
                addrs.append(f"{ref.get('namespace', 'default')}/{ref['name']}")
    return Endpoints(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        addresses=addrs,
        resource_version=str(meta.get("resourceVersion", "")),
    )


def endpoints_to_k8s(ep: Endpoints) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Endpoints",
        "metadata": {"name": ep.name, "namespace": ep.namespace},
        "subsets": [
            {
                "addresses": [
                    {
                        "targetRef": {
                            "kind": "Pod",
                            "namespace": a.split("/", 1)[0],
                            "name": a.split("/", 1)[1],
                        }
                    }
                    for a in ep.addresses
                ]
            }
        ] if ep.addresses else [],
    }


@dataclass
class StatefulSet:
    """apps/v1 StatefulSet — the controller subset: stable ordinal
    identities name-0..name-(replicas-1), OrderedReady rollout
    (pkg/apis/apps/types.go StatefulSetSpec; reconciled by
    pkg/controller/statefulset/stateful_set.go)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None
    service_name: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class DaemonSet:
    """apps/v1 DaemonSet — one pod per eligible node, placed through the
    DEFAULT scheduler via a per-node matchFields node-affinity pin
    (ScheduleDaemonSetPods, pkg/controller/daemon/daemon_controller.go
    nodeShouldRunDaemonPod + util.ReplaceDaemonSetPodNodeNameNodeAffinity)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Namespace:
    """core/v1 Namespace — lifecycle subset: Active → Terminating drains
    every namespaced object, then the namespace object goes away
    (pkg/controller/namespace/deletion/namespaced_resources_deleter.go)."""

    name: str = ""
    phase: str = "Active"  # Active | Terminating
    resource_version: str = ""

    def key(self) -> str:
        return self.name


def namespace_from_k8s(obj: dict) -> Namespace:
    meta = obj.get("metadata") or {}
    status = obj.get("status") or {}
    return Namespace(
        name=meta.get("name", ""),
        phase=status.get("phase", "Active"),
        resource_version=str(meta.get("resourceVersion", "")),
    )


def namespace_to_k8s(ns: Namespace) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": {"name": ns.name},
        "status": {"phase": ns.phase},
    }


def _request_value(resource_name: str, q: Quantity) -> int:
    if resource_name == RESOURCE_CPU:
        return q.milli_value()
    return q.value()


def is_extended_resource_name(name: str) -> bool:
    """v1helper.IsExtendedResourceName (pkg/apis/core/v1/helper/helpers.go:38):
    extended = not native and not 'requests.'-prefixed. Native
    (IsNativeResource, helpers.go:59) = no domain at all, or the
    kubernetes.io/ domain."""
    if name.startswith("requests."):
        return False
    is_native = "/" not in name or "kubernetes.io/" in name
    return not is_native


# ---------------------------------------------------------------------------
# k8s JSON wire conversion
# ---------------------------------------------------------------------------

def _qmap(d: Optional[Dict[str, str]]) -> Dict[str, Quantity]:
    return {k: parse_quantity(v) for k, v in (d or {}).items()}


def _nsr_from(d: dict) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=d.get("key", ""), operator=d.get("operator", ""), values=list(d.get("values") or [])
    )


def _term_from(d: dict) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=[_nsr_from(e) for e in d.get("matchExpressions") or []],
        match_fields=[_nsr_from(e) for e in d.get("matchFields") or []],
    )


def _label_selector_from(d: Optional[dict]) -> Optional[LabelSelector]:
    if d is None:
        return None
    return LabelSelector(
        match_labels=dict(d.get("matchLabels") or {}),
        match_expressions=[
            LabelSelectorRequirement(
                key=e.get("key", ""), operator=e.get("operator", ""), values=list(e.get("values") or [])
            )
            for e in d.get("matchExpressions") or []
        ],
    )


def _pod_affinity_term_from(d: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_label_selector_from(d.get("labelSelector")),
        namespaces=list(d.get("namespaces") or []),
        topology_key=d.get("topologyKey", ""),
    )


def _affinity_from(d: Optional[dict]) -> Optional[Affinity]:
    if not d:
        return None
    aff = Affinity()
    na = d.get("nodeAffinity")
    if na:
        req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
        aff.node_affinity = NodeAffinity(
            required=NodeSelector([_term_from(t) for t in req.get("nodeSelectorTerms") or []])
            if req is not None
            else None,
            preferred=[
                PreferredSchedulingTerm(weight=p.get("weight", 0), preference=_term_from(p.get("preference") or {}))
                for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
            ],
        )
    for attr, key, cls in (
        ("pod_affinity", "podAffinity", PodAffinity),
        ("pod_anti_affinity", "podAntiAffinity", PodAntiAffinity),
    ):
        pa = d.get(key)
        if pa:
            setattr(
                aff,
                attr,
                cls(
                    required=[
                        _pod_affinity_term_from(t)
                        for t in pa.get("requiredDuringSchedulingIgnoredDuringExecution") or []
                    ],
                    preferred=[
                        WeightedPodAffinityTerm(
                            weight=w.get("weight", 0),
                            pod_affinity_term=_pod_affinity_term_from(w.get("podAffinityTerm") or {}),
                        )
                        for w in pa.get("preferredDuringSchedulingIgnoredDuringExecution") or []
                    ],
                ),
            )
    return aff


def _container_from(d: dict) -> Container:
    res = d.get("resources") or {}
    return Container(
        name=d.get("name", ""),
        image=d.get("image", ""),
        ports=[
            ContainerPort(
                host_port=p.get("hostPort", 0),
                container_port=p.get("containerPort", 0),
                protocol=p.get("protocol", "TCP"),
                host_ip=p.get("hostIP", ""),
            )
            for p in d.get("ports") or []
        ],
        requests=_qmap(res.get("requests")),
        limits=_qmap(res.get("limits")),
    )


def _parse_time(v) -> Optional[float]:
    """metav1.Time: RFC3339 string -> epoch seconds (also accepts numbers)."""
    if v is None:
        return None
    if isinstance(v, (int, float)):
        return float(v)
    import datetime

    try:
        return datetime.datetime.fromisoformat(str(v).replace("Z", "+00:00")).timestamp()
    except ValueError:
        return None


def _format_time(t: float) -> str:
    import datetime

    return (
        datetime.datetime.fromtimestamp(t, tz=datetime.timezone.utc)
        .isoformat(timespec="seconds")
        .replace("+00:00", "Z")
    )


def _volume_from(d: dict) -> Volume:
    v = Volume(name=d.get("name", ""))
    pvc = d.get("persistentVolumeClaim")
    if pvc:
        v.pvc_claim_name = pvc.get("claimName", "")
    gce = d.get("gcePersistentDisk")
    if gce:
        v.gce_pd_name = gce.get("pdName", "")
        v.gce_pd_read_only = bool(gce.get("readOnly", False))
    aws = d.get("awsElasticBlockStore")
    if aws:
        v.aws_volume_id = aws.get("volumeID", "")
        v.aws_read_only = bool(aws.get("readOnly", False))
    az = d.get("azureDisk")
    if az:
        v.azure_disk_name = az.get("diskName", "")
    rbd = d.get("rbd")
    if rbd:
        v.rbd_pool = rbd.get("pool", "rbd")
        v.rbd_image = rbd.get("image", "")
        v.rbd_monitors = tuple(rbd.get("monitors") or [])
        v.rbd_read_only = bool(rbd.get("readOnly", False))
    iscsi = d.get("iscsi")
    if iscsi:
        v.iscsi_target_portal = iscsi.get("targetPortal", "")
        v.iscsi_iqn = iscsi.get("iqn", "")
        v.iscsi_lun = int(iscsi.get("lun", 0))
        v.iscsi_read_only = bool(iscsi.get("readOnly", False))
    return v


def _volume_to(v: Volume) -> dict:
    d: Dict[str, Any] = {"name": v.name}
    if v.pvc_claim_name:
        d["persistentVolumeClaim"] = {"claimName": v.pvc_claim_name}
    if v.gce_pd_name:
        d["gcePersistentDisk"] = {"pdName": v.gce_pd_name, "readOnly": v.gce_pd_read_only}
    if v.aws_volume_id:
        d["awsElasticBlockStore"] = {"volumeID": v.aws_volume_id, "readOnly": v.aws_read_only}
    if v.azure_disk_name:
        d["azureDisk"] = {"diskName": v.azure_disk_name}
    if v.rbd_image:
        d["rbd"] = {
            "pool": v.rbd_pool,
            "image": v.rbd_image,
            "monitors": list(v.rbd_monitors),
            "readOnly": v.rbd_read_only,
        }
    if v.iscsi_iqn:
        d["iscsi"] = {
            "targetPortal": v.iscsi_target_portal,
            "iqn": v.iscsi_iqn,
            "lun": v.iscsi_lun,
            "readOnly": v.iscsi_read_only,
        }
    return d


def pod_from_k8s(obj: dict) -> Pod:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    pod = Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        resource_version=str(meta.get("resourceVersion", "")),
        owner_references=list(meta.get("ownerReferences") or []),
        node_name=spec.get("nodeName", ""),
        **(
            {"creation_timestamp": _parse_time(meta.get("creationTimestamp"))}
            if _parse_time(meta.get("creationTimestamp")) is not None
            else {}  # unparseable -> default_factory now() (never None)
        ),
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=_affinity_from(spec.get("affinity")),
        tolerations=[
            Toleration(
                key=t.get("key", ""),
                operator=t.get("operator", "Equal"),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
                toleration_seconds=t.get("tolerationSeconds"),
            )
            for t in spec.get("tolerations") or []
        ],
        containers=[_container_from(c) for c in spec.get("containers") or []],
        init_containers=[_container_from(c) for c in spec.get("initContainers") or []],
        overhead=_qmap(spec.get("overhead")),
        priority=spec.get("priority"),
        priority_class_name=spec.get("priorityClassName", ""),
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=c.get("maxSkew", 1),
                topology_key=c.get("topologyKey", ""),
                when_unsatisfiable=c.get("whenUnsatisfiable", DO_NOT_SCHEDULE),
                label_selector=_label_selector_from(c.get("labelSelector")),
            )
            for c in spec.get("topologySpreadConstraints") or []
        ],
        scheduler_name=spec.get("schedulerName", "default-scheduler"),
        host_network=bool(spec.get("hostNetwork", False)),
        volumes=[_volume_from(v) for v in spec.get("volumes") or []],
        phase=status.get("phase", "Pending"),
        nominated_node_name=status.get("nominatedNodeName", ""),
        conditions=list(status.get("conditions") or []),
    )
    pod.deletion_timestamp = _parse_time(meta.get("deletionTimestamp"))
    return pod


def node_from_k8s(obj: dict) -> Node:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return Node(
        name=meta.get("name", ""),
        uid=meta.get("uid") or _new_uid(),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        resource_version=str(meta.get("resourceVersion", "")),
        unschedulable=bool(spec.get("unschedulable", False)),
        taints=[
            Taint(key=t.get("key", ""), value=t.get("value", ""), effect=t.get("effect", ""))
            for t in spec.get("taints") or []
        ],
        capacity=_qmap(status.get("capacity")),
        allocatable=_qmap(status.get("allocatable")),
        images=[
            ContainerImage(names=list(i.get("names") or []), size_bytes=i.get("sizeBytes", 0))
            for i in status.get("images") or []
        ],
        conditions=list(status.get("conditions") or []),
    )


def _quantity_str(name: str, v: Quantity) -> str:
    if name == RESOURCE_CPU:
        return f"{v.milli_value()}m"
    return str(v.value())


def pod_to_k8s(pod: Pod) -> dict:
    def container_to(c: Container) -> dict:
        d: Dict[str, Any] = {"name": c.name, "image": c.image}
        if c.ports:
            d["ports"] = [
                {
                    "hostPort": p.host_port,
                    "containerPort": p.container_port,
                    "protocol": p.protocol,
                    **({"hostIP": p.host_ip} if p.host_ip else {}),
                }
                for p in c.ports
            ]
        if c.requests:
            d.setdefault("resources", {})["requests"] = {
                k: _quantity_str(k, v) for k, v in c.requests.items()
            }
        if c.limits:
            d.setdefault("resources", {})["limits"] = {k: _quantity_str(k, v) for k, v in c.limits.items()}
        return d

    spec: Dict[str, Any] = {
        "containers": [container_to(c) for c in pod.containers],
        "schedulerName": pod.scheduler_name,
    }
    if pod.init_containers:
        spec["initContainers"] = [container_to(c) for c in pod.init_containers]
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.priority is not None:
        spec["priority"] = pod.priority
    if pod.priority_class_name:
        spec["priorityClassName"] = pod.priority_class_name
    if pod.overhead:
        spec["overhead"] = {k: _quantity_str(k, v) for k, v in pod.overhead.items()}
    if pod.host_network:
        spec["hostNetwork"] = True
    if pod.tolerations:
        spec["tolerations"] = [
            {
                "key": t.key, "operator": t.operator, "value": t.value,
                "effect": t.effect,
                **(
                    {"tolerationSeconds": t.toleration_seconds}
                    if t.toleration_seconds is not None
                    else {}
                ),
            }
            for t in pod.tolerations
        ]
    if pod.topology_spread_constraints:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": c.max_skew,
                "topologyKey": c.topology_key,
                "whenUnsatisfiable": c.when_unsatisfiable,
                **(
                    {"labelSelector": _label_selector_to(c.label_selector)}
                    if c.label_selector is not None
                    else {}
                ),
            }
            for c in pod.topology_spread_constraints
        ]
    if pod.affinity is not None:
        spec["affinity"] = _affinity_to(pod.affinity)
    if pod.volumes:
        spec["volumes"] = [_volume_to(v) for v in pod.volumes]
    status: Dict[str, Any] = {"phase": pod.phase}
    if pod.nominated_node_name:
        status["nominatedNodeName"] = pod.nominated_node_name
    if pod.conditions:
        status["conditions"] = list(pod.conditions)
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.labels),
            "annotations": dict(pod.annotations),
            "resourceVersion": pod.resource_version,
            "creationTimestamp": _format_time(pod.creation_timestamp),
            **(
                {"deletionTimestamp": _format_time(pod.deletion_timestamp)}
                if pod.deletion_timestamp is not None
                else {}
            ),
            **({"ownerReferences": pod.owner_references} if pod.owner_references else {}),
        },
        "spec": spec,
        "status": status,
    }


@dataclass
class Deployment:
    """apps/v1 Deployment — the controller subset: desired replicas +
    selector + pod template (reconciled to template-hash ReplicaSets by
    pkg/controller/deployment)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    replicas: int = 1
    selector: Optional[LabelSelector] = None
    template: Optional[Pod] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class Job:
    """batch/v1 Job — the controller subset: parallelism + completions +
    template (pkg/apis/batch/types.go JobSpec; reconciled by
    pkg/controller/job)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    parallelism: int = 1
    completions: int = 1
    template: Optional[Pod] = None
    # TTL-after-finished (alpha in this reference era,
    # pkg/controller/ttlafterfinished/ttlafterfinished_controller.go)
    ttl_seconds_after_finished: Optional[int] = None
    owner_references: List[Dict[str, Any]] = field(default_factory=list)
    # status (job_controller.go syncJob's status update)
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    completion_time: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def job_from_k8s(obj: dict) -> Job:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    tmpl = spec.get("template")
    template = None
    if tmpl is not None:
        tmeta = dict(tmpl.get("metadata") or {})
        tmeta.setdefault("namespace", meta.get("namespace", "default"))
        tmeta.setdefault("name", meta.get("name", "") + "-template")
        template = pod_from_k8s({"metadata": tmeta, "spec": tmpl.get("spec") or {}})
    return Job(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        resource_version=str(meta.get("resourceVersion", "")),
        # explicit 0 is the standard way to SUSPEND a Job — distinct from
        # absent (defaults to 1); None also means absent
        parallelism=int(spec.get("parallelism") if spec.get("parallelism") is not None else 1),
        completions=int(spec.get("completions") if spec.get("completions") is not None else 1),
        template=template,
        ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
        owner_references=list(meta.get("ownerReferences") or []),
        active=int(status.get("active", 0)),
        succeeded=int(status.get("succeeded", 0)),
        failed=int(status.get("failed", 0)),
        completion_time=_parse_time(status.get("completionTime")),
    )


def job_to_k8s(job: Job) -> dict:
    spec: Dict[str, Any] = {
        "parallelism": job.parallelism,
        "completions": job.completions,
    }
    if job.ttl_seconds_after_finished is not None:
        spec["ttlSecondsAfterFinished"] = job.ttl_seconds_after_finished
    if job.template is not None:
        t = pod_to_k8s(job.template)
        spec["template"] = {
            "metadata": {"labels": t["metadata"].get("labels", {})},
            "spec": t["spec"],
        }
    meta: Dict[str, Any] = {"name": job.name, "namespace": job.namespace, "uid": job.uid}
    if job.resource_version:
        meta["resourceVersion"] = job.resource_version
    if job.owner_references:
        meta["ownerReferences"] = list(job.owner_references)
    status: Dict[str, Any] = {
        "active": job.active, "succeeded": job.succeeded, "failed": job.failed,
    }
    if job.completion_time is not None:
        status["completionTime"] = _format_time(job.completion_time)
    return {"apiVersion": "batch/v1", "kind": "Job", "metadata": meta, "spec": spec,
            "status": status}


def deployment_from_k8s(obj: dict) -> Deployment:
    rs = replicaset_from_k8s(obj)
    return Deployment(
        name=rs.name, namespace=rs.namespace, uid=rs.uid,
        resource_version=rs.resource_version, replicas=rs.replicas,
        selector=rs.selector, template=rs.template,
    )


def deployment_to_k8s(dep: Deployment) -> dict:
    d = replicaset_to_k8s(ReplicaSet(
        name=dep.name, namespace=dep.namespace, uid=dep.uid,
        resource_version=dep.resource_version, replicas=dep.replicas,
        selector=dep.selector, template=dep.template,
    ))
    d["kind"] = "Deployment"
    return d


def replicaset_from_k8s(obj: dict) -> ReplicaSet:
    """apps/v1 ReplicaSet JSON → ReplicaSet (the controller subset)."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    tmpl = spec.get("template")
    template = None
    if tmpl is not None:
        tmeta = dict(tmpl.get("metadata") or {})
        tmeta.setdefault("namespace", meta.get("namespace", "default"))
        tmeta.setdefault("name", meta.get("name", "") + "-template")
        template = pod_from_k8s({"metadata": tmeta, "spec": tmpl.get("spec") or {}})
    return ReplicaSet(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        resource_version=str(meta.get("resourceVersion", "")),
        replicas=int(spec.get("replicas") if spec.get("replicas") is not None else 1),
        selector=_label_selector_from(spec.get("selector")),
        template=template,
        owner_references=list(meta.get("ownerReferences") or []),
    )


def _workload_from_k8s(cls, api_kind: str, obj: dict, extra=None):
    """Shared apps/v1 workload decode (StatefulSet/DaemonSet: metadata +
    selector + pod template [+ replicas where present])."""
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    tmpl = spec.get("template")
    template = None
    if tmpl is not None:
        tmeta = dict(tmpl.get("metadata") or {})
        tmeta.setdefault("namespace", meta.get("namespace", "default"))
        tmeta.setdefault("name", meta.get("name", "") + "-template")
        template = pod_from_k8s({"metadata": tmeta, "spec": tmpl.get("spec") or {}})
    kw = dict(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        resource_version=str(meta.get("resourceVersion", "")),
        selector=_label_selector_from(spec.get("selector")),
        template=template,
    )
    if extra:
        kw.update(extra(spec))
    return cls(**kw)


def _workload_to_k8s(obj, api_kind: str, extra_spec=None) -> dict:
    spec: Dict[str, Any] = {}
    if getattr(obj, "replicas", None) is not None:
        spec["replicas"] = obj.replicas
    if obj.selector is not None:
        spec["selector"] = _label_selector_to(obj.selector)
    if obj.template is not None:
        t = pod_to_k8s(obj.template)
        spec["template"] = {
            "metadata": {"labels": t["metadata"].get("labels", {})},
            "spec": t["spec"],
        }
    if extra_spec:
        spec.update(extra_spec)
    meta: Dict[str, Any] = {"name": obj.name, "namespace": obj.namespace, "uid": obj.uid}
    if obj.resource_version:
        meta["resourceVersion"] = obj.resource_version
    return {"apiVersion": "apps/v1", "kind": api_kind, "metadata": meta, "spec": spec}


def statefulset_from_k8s(obj: dict) -> StatefulSet:
    return _workload_from_k8s(
        StatefulSet, "StatefulSet", obj,
        extra=lambda spec: {
            "replicas": int(spec.get("replicas") if spec.get("replicas") is not None else 1),
            "service_name": spec.get("serviceName", ""),
        },
    )


def statefulset_to_k8s(ss: StatefulSet) -> dict:
    return _workload_to_k8s(ss, "StatefulSet", {"serviceName": ss.service_name})


def daemonset_from_k8s(obj: dict) -> DaemonSet:
    return _workload_from_k8s(DaemonSet, "DaemonSet", obj)


def daemonset_to_k8s(ds: DaemonSet) -> dict:
    return _workload_to_k8s(ds, "DaemonSet")  # no replicas attr → none emitted


def replicaset_to_k8s(rs: ReplicaSet) -> dict:
    spec: Dict[str, Any] = {"replicas": rs.replicas}
    if rs.selector is not None:
        spec["selector"] = _label_selector_to(rs.selector)
    if rs.template is not None:
        t = pod_to_k8s(rs.template)
        spec["template"] = {
            "metadata": {"labels": t["metadata"].get("labels", {})},
            "spec": t["spec"],
        }
    meta: Dict[str, Any] = {"name": rs.name, "namespace": rs.namespace, "uid": rs.uid}
    if rs.resource_version:
        meta["resourceVersion"] = rs.resource_version
    if rs.owner_references:
        meta["ownerReferences"] = list(rs.owner_references)
    return {
        "apiVersion": "apps/v1",
        "kind": "ReplicaSet",
        "metadata": meta,
        "spec": spec,
    }


def _label_selector_to(s: LabelSelector) -> dict:
    d: Dict[str, Any] = {}
    if s.match_labels:
        d["matchLabels"] = dict(s.match_labels)
    if s.match_expressions:
        d["matchExpressions"] = [
            {"key": e.key, "operator": e.operator, "values": list(e.values)} for e in s.match_expressions
        ]
    return d


def _term_to(t: NodeSelectorTerm) -> dict:
    return {
        "matchExpressions": [
            {"key": e.key, "operator": e.operator, "values": list(e.values)} for e in t.match_expressions
        ],
        **(
            {
                "matchFields": [
                    {"key": e.key, "operator": e.operator, "values": list(e.values)}
                    for e in t.match_fields
                ]
            }
            if t.match_fields
            else {}
        ),
    }


def _affinity_to(aff: Affinity) -> dict:
    d: Dict[str, Any] = {}
    if aff.node_affinity is not None:
        na: Dict[str, Any] = {}
        if aff.node_affinity.required is not None:
            na["requiredDuringSchedulingIgnoredDuringExecution"] = {
                "nodeSelectorTerms": [_term_to(t) for t in aff.node_affinity.required.node_selector_terms]
            }
        if aff.node_affinity.preferred:
            na["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": p.weight, "preference": _term_to(p.preference)}
                for p in aff.node_affinity.preferred
            ]
        d["nodeAffinity"] = na
    for attr, key in (("pod_affinity", "podAffinity"), ("pod_anti_affinity", "podAntiAffinity")):
        pa = getattr(aff, attr)
        if pa is not None:
            e: Dict[str, Any] = {}
            if pa.required:
                e["requiredDuringSchedulingIgnoredDuringExecution"] = [
                    {
                        "labelSelector": _label_selector_to(t.label_selector)
                        if t.label_selector is not None
                        else None,
                        "namespaces": list(t.namespaces),
                        "topologyKey": t.topology_key,
                    }
                    for t in pa.required
                ]
            if pa.preferred:
                e["preferredDuringSchedulingIgnoredDuringExecution"] = [
                    {
                        "weight": w.weight,
                        "podAffinityTerm": {
                            "labelSelector": _label_selector_to(w.pod_affinity_term.label_selector)
                            if w.pod_affinity_term.label_selector is not None
                            else None,
                            "namespaces": list(w.pod_affinity_term.namespaces),
                            "topologyKey": w.pod_affinity_term.topology_key,
                        },
                    }
                    for w in pa.preferred
                ]
            d[key] = e
    return d


def pdb_from_k8s(obj: dict) -> PodDisruptionBudget:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return PodDisruptionBudget(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        selector=_label_selector_from(spec.get("selector")),
        min_available=spec.get("minAvailable"),
        max_unavailable=spec.get("maxUnavailable"),
        disruptions_allowed=int(status.get("disruptionsAllowed", 0)),
        current_healthy=int(status.get("currentHealthy", 0)),
        desired_healthy=int(status.get("desiredHealthy", 0)),
        expected_pods=int(status.get("expectedPods", 0)),
    )


def pdb_to_k8s(pdb: PodDisruptionBudget) -> dict:
    meta: Dict[str, Any] = {"name": pdb.name, "namespace": pdb.namespace}
    if pdb.resource_version:
        meta["resourceVersion"] = pdb.resource_version
    spec: Dict[str, Any] = {}
    if pdb.selector is not None:
        spec["selector"] = _label_selector_to(pdb.selector)
    if pdb.min_available is not None:
        spec["minAvailable"] = pdb.min_available
    if pdb.max_unavailable is not None:
        spec["maxUnavailable"] = pdb.max_unavailable
    return {
        "apiVersion": "policy/v1beta1",
        "kind": "PodDisruptionBudget",
        "metadata": meta,
        "spec": spec,
        "status": {
            "disruptionsAllowed": pdb.disruptions_allowed,
            "currentHealthy": pdb.current_healthy,
            "desiredHealthy": pdb.desired_healthy,
            "expectedPods": pdb.expected_pods,
        },
    }


@dataclass
class ReplicationController:
    """core/v1 ReplicationController — the original replica manager
    (pkg/controller/replication/replication_controller.go is a thin
    adapter over the ReplicaSet reconciler; the wire selector is a plain
    map, not a LabelSelector)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    replicas: int = 1
    selector: Optional[LabelSelector] = None  # converted from the v1 map
    template: Optional[Pod] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def replicationcontroller_from_k8s(obj: dict) -> ReplicationController:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    tmpl = spec.get("template")
    template = None
    if tmpl is not None:
        tmeta = dict(tmpl.get("metadata") or {})
        tmeta.setdefault("namespace", meta.get("namespace", "default"))
        tmeta.setdefault("name", meta.get("name", "") + "-template")
        template = pod_from_k8s({"metadata": tmeta, "spec": tmpl.get("spec") or {}})
    sel = spec.get("selector")
    return ReplicationController(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        resource_version=str(meta.get("resourceVersion", "")),
        replicas=int(spec.get("replicas") if spec.get("replicas") is not None else 1),
        selector=LabelSelector(match_labels=dict(sel)) if sel else None,
        template=template,
    )


def replicationcontroller_to_k8s(rc: ReplicationController) -> dict:
    spec: Dict[str, Any] = {"replicas": rc.replicas}
    if rc.selector is not None:
        spec["selector"] = dict(rc.selector.match_labels)
    if rc.template is not None:
        t = pod_to_k8s(rc.template)
        spec["template"] = {
            "metadata": {"labels": t["metadata"].get("labels", {})},
            "spec": t["spec"],
        }
    meta: Dict[str, Any] = {"name": rc.name, "namespace": rc.namespace, "uid": rc.uid}
    if rc.resource_version:
        meta["resourceVersion"] = rc.resource_version
    return {"apiVersion": "v1", "kind": "ReplicationController", "metadata": meta, "spec": spec}


@dataclass
class CronJob:
    """batch/v1beta1 CronJob (pkg/apis/batch/types.go CronJobSpec;
    reconciled by pkg/controller/cronjob — the reference's syncAll polls
    every 10s rather than watching). The job template carries the Job
    spec subset (parallelism/completions/pod template)."""

    name: str = ""
    namespace: str = "default"
    uid: str = field(default_factory=_new_uid)
    resource_version: str = ""
    creation_timestamp: float = field(default_factory=time.time)
    schedule: str = "* * * * *"
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    job_template: Optional[Job] = None
    # status
    last_schedule_time: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def cronjob_from_k8s(obj: dict) -> CronJob:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    jt = spec.get("jobTemplate")
    job_template = None
    if jt is not None:
        job_template = job_from_k8s({
            "metadata": {"name": meta.get("name", ""), "namespace": meta.get("namespace", "default")},
            "spec": jt.get("spec") or {},
        })
    return CronJob(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        uid=meta.get("uid") or _new_uid(),
        resource_version=str(meta.get("resourceVersion", "")),
        **({"creation_timestamp": _parse_time(meta.get("creationTimestamp"))}
           if _parse_time(meta.get("creationTimestamp")) is not None else {}),
        schedule=spec.get("schedule", "* * * * *"),
        suspend=bool(spec.get("suspend", False)),
        concurrency_policy=spec.get("concurrencyPolicy", "Allow"),
        job_template=job_template,
        last_schedule_time=_parse_time(status.get("lastScheduleTime")),
    )


def cronjob_to_k8s(cj: CronJob) -> dict:
    meta: Dict[str, Any] = {"name": cj.name, "namespace": cj.namespace, "uid": cj.uid,
                            "creationTimestamp": _format_time(cj.creation_timestamp)}
    if cj.resource_version:
        meta["resourceVersion"] = cj.resource_version
    spec: Dict[str, Any] = {
        "schedule": cj.schedule,
        "suspend": cj.suspend,
        "concurrencyPolicy": cj.concurrency_policy,
    }
    if cj.job_template is not None:
        spec["jobTemplate"] = {"spec": job_to_k8s(cj.job_template)["spec"]}
    out = {"apiVersion": "batch/v1beta1", "kind": "CronJob", "metadata": meta, "spec": spec}
    if cj.last_schedule_time is not None:
        out["status"] = {"lastScheduleTime": _format_time(cj.last_schedule_time)}
    return out


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota: spec.hard caps aggregate usage per namespace
    (counts and request/limit sums); status.used is recomputed by the
    resourcequota controller (pkg/controller/resourcequota) and enforced
    at admission (plugin/pkg/admission/resourcequota)."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    hard: Dict[str, int] = field(default_factory=dict)  # scheduler units (cpu→milli)
    used: Dict[str, int] = field(default_factory=dict)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def _quota_amounts_from(d: Optional[dict]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for k, v in (d or {}).items():
        base = k.split(".", 1)[1] if k.startswith(("requests.", "limits.")) else k
        out[k] = _request_value(base, parse_quantity(str(v)))
    return out


def _quota_amounts_to(d: Dict[str, int]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for k, v in d.items():
        base = k.split(".", 1)[1] if k.startswith(("requests.", "limits.")) else k
        out[k] = f"{v}m" if base == RESOURCE_CPU else str(v)
    return out


def resourcequota_from_k8s(obj: dict) -> ResourceQuota:
    meta = obj.get("metadata") or {}
    return ResourceQuota(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        hard=_quota_amounts_from((obj.get("spec") or {}).get("hard")),
        used=_quota_amounts_from((obj.get("status") or {}).get("used")),
    )


def resourcequota_to_k8s(rq: ResourceQuota) -> dict:
    meta: Dict[str, Any] = {"name": rq.name, "namespace": rq.namespace}
    if rq.resource_version:
        meta["resourceVersion"] = rq.resource_version
    return {
        "apiVersion": "v1",
        "kind": "ResourceQuota",
        "metadata": meta,
        "spec": {"hard": _quota_amounts_to(rq.hard)},
        "status": {"hard": _quota_amounts_to(rq.hard), "used": _quota_amounts_to(rq.used)},
    }


@dataclass
class LimitRangeItem:
    """One v1 LimitRangeItem (type Container is what the LimitRanger
    admission plugin defaults from)."""

    type: str = "Container"
    default: Dict[str, Quantity] = field(default_factory=dict)  # limits default
    default_request: Dict[str, Quantity] = field(default_factory=dict)
    max: Dict[str, Quantity] = field(default_factory=dict)
    min: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class LimitRange:
    """core/v1 LimitRange — consumed by the LimitRanger admission plugin
    (plugin/pkg/admission/limitranger/admission.go): defaults container
    requests/limits and enforces min/max at pod-create time."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    limits: List[LimitRangeItem] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def limitrange_from_k8s(obj: dict) -> LimitRange:
    meta = obj.get("metadata") or {}
    items = []
    for it in (obj.get("spec") or {}).get("limits") or []:
        items.append(LimitRangeItem(
            type=it.get("type", "Container"),
            default=_qmap(it.get("default")),
            default_request=_qmap(it.get("defaultRequest")),
            max=_qmap(it.get("max")),
            min=_qmap(it.get("min")),
        ))
    return LimitRange(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        limits=items,
    )


def limitrange_to_k8s(lr: LimitRange) -> dict:
    meta: Dict[str, Any] = {"name": lr.name, "namespace": lr.namespace}
    if lr.resource_version:
        meta["resourceVersion"] = lr.resource_version
    return {
        "apiVersion": "v1",
        "kind": "LimitRange",
        "metadata": meta,
        "spec": {"limits": [
            {
                "type": it.type,
                **({"default": {k: _quantity_str(k, v) for k, v in it.default.items()}} if it.default else {}),
                **({"defaultRequest": {k: _quantity_str(k, v) for k, v in it.default_request.items()}} if it.default_request else {}),
                **({"max": {k: _quantity_str(k, v) for k, v in it.max.items()}} if it.max else {}),
                **({"min": {k: _quantity_str(k, v) for k, v in it.min.items()}} if it.min else {}),
            }
            for it in lr.limits
        ]},
    }


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount — identity subset; the serviceaccount
    controller (pkg/controller/serviceaccount) guarantees 'default' exists
    in every namespace."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def serviceaccount_from_k8s(obj: dict) -> ServiceAccount:
    meta = obj.get("metadata") or {}
    return ServiceAccount(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
    )


# ---------------------------------------------------------------------------
# RBAC (rbac.authorization.k8s.io/v1; staging/src/k8s.io/api/rbac/v1/types.go,
# evaluated by plugin/pkg/auth/authorizer/rbac/rbac.go)
# ---------------------------------------------------------------------------

@dataclass
class PolicyRule:
    """rbac/v1 PolicyRule subset: verbs × resources, '*' wildcards
    (rbac.go RuleAllows / VerbMatches / ResourceMatches)."""

    verbs: List[str] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)


@dataclass
class RoleRef:
    kind: str = "ClusterRole"  # ClusterRole | Role
    name: str = ""


@dataclass
class Subject:
    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""  # ServiceAccount subjects only


@dataclass
class Role:
    """Namespaced rule set; granted inside its namespace via RoleBinding."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    rules: List[PolicyRule] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ClusterRole:
    """Cluster-scoped rule set; granted everywhere via ClusterRoleBinding
    or inside one namespace via RoleBinding (rbac.go appliesTo)."""

    name: str = ""
    resource_version: str = ""
    rules: List[PolicyRule] = field(default_factory=list)

    def key(self) -> str:
        return self.name


@dataclass
class RoleBinding:
    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    role_ref: RoleRef = field(default_factory=RoleRef)
    subjects: List[Subject] = field(default_factory=list)

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ClusterRoleBinding:
    name: str = ""
    resource_version: str = ""
    role_ref: RoleRef = field(default_factory=RoleRef)
    subjects: List[Subject] = field(default_factory=list)

    def key(self) -> str:
        return self.name


def _rules_from(items) -> List[PolicyRule]:
    return [PolicyRule(verbs=list(r.get("verbs") or []),
                       resources=list(r.get("resources") or []))
            for r in (items or [])]


def _rules_to(rules: List[PolicyRule]) -> List[dict]:
    return [{"verbs": list(r.verbs), "resources": list(r.resources)}
            for r in rules]


def _subjects_from(items) -> List[Subject]:
    return [Subject(kind=s.get("kind", "User"), name=s.get("name", ""),
                    namespace=s.get("namespace", ""))
            for s in (items or [])]


def _subjects_to(subjects: List[Subject]) -> List[dict]:
    return [{"kind": s.kind, "name": s.name,
             **({"namespace": s.namespace} if s.namespace else {})}
            for s in subjects]


def role_from_k8s(obj: dict) -> Role:
    meta = obj.get("metadata") or {}
    return Role(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        rules=_rules_from(obj.get("rules")),
    )


def role_to_k8s(r: Role) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "Role",
        "metadata": {"name": r.name, "namespace": r.namespace,
                     **({"resourceVersion": r.resource_version} if r.resource_version else {})},
        "rules": _rules_to(r.rules),
    }


def clusterrole_from_k8s(obj: dict) -> ClusterRole:
    meta = obj.get("metadata") or {}
    return ClusterRole(
        name=meta.get("name", ""),
        resource_version=str(meta.get("resourceVersion", "")),
        rules=_rules_from(obj.get("rules")),
    )


def clusterrole_to_k8s(r: ClusterRole) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
        "metadata": {"name": r.name,
                     **({"resourceVersion": r.resource_version} if r.resource_version else {})},
        "rules": _rules_to(r.rules),
    }


def _roleref_from(d) -> RoleRef:
    d = d or {}
    return RoleRef(kind=d.get("kind", "ClusterRole"), name=d.get("name", ""))


def rolebinding_from_k8s(obj: dict) -> RoleBinding:
    meta = obj.get("metadata") or {}
    return RoleBinding(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        role_ref=_roleref_from(obj.get("roleRef")),
        subjects=_subjects_from(obj.get("subjects")),
    )


def rolebinding_to_k8s(b: RoleBinding) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
        "metadata": {"name": b.name, "namespace": b.namespace,
                     **({"resourceVersion": b.resource_version} if b.resource_version else {})},
        "roleRef": {"kind": b.role_ref.kind, "name": b.role_ref.name},
        "subjects": _subjects_to(b.subjects),
    }


def clusterrolebinding_from_k8s(obj: dict) -> ClusterRoleBinding:
    meta = obj.get("metadata") or {}
    return ClusterRoleBinding(
        name=meta.get("name", ""),
        resource_version=str(meta.get("resourceVersion", "")),
        role_ref=_roleref_from(obj.get("roleRef")),
        subjects=_subjects_from(obj.get("subjects")),
    )


def clusterrolebinding_to_k8s(b: ClusterRoleBinding) -> dict:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRoleBinding",
        "metadata": {"name": b.name,
                     **({"resourceVersion": b.resource_version} if b.resource_version else {})},
        "roleRef": {"kind": b.role_ref.kind, "name": b.role_ref.name},
        "subjects": _subjects_to(b.subjects),
    }


def serviceaccount_to_k8s(sa: ServiceAccount) -> dict:
    meta: Dict[str, Any] = {"name": sa.name, "namespace": sa.namespace}
    if sa.resource_version:
        meta["resourceVersion"] = sa.resource_version
    return {"apiVersion": "v1", "kind": "ServiceAccount", "metadata": meta}


@dataclass
class HorizontalPodAutoscaler:
    """autoscaling/v1 HorizontalPodAutoscaler (pkg/apis/autoscaling;
    reconciled by pkg/controller/podautoscaler): scales the target
    workload's replicas toward targetCPUUtilizationPercentage using the
    pod metrics the metrics kinds serve."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    # spec
    target_kind: str = "Deployment"
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization_pct: int = 80
    # status
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_pct: Optional[int] = None
    last_scale_time: Optional[float] = None

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def hpa_from_k8s(obj: dict) -> HorizontalPodAutoscaler:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    ref = spec.get("scaleTargetRef") or {}
    return HorizontalPodAutoscaler(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        target_kind=ref.get("kind", "Deployment"),
        target_name=ref.get("name", ""),
        min_replicas=int(spec.get("minReplicas") if spec.get("minReplicas") is not None else 1),
        max_replicas=int(spec.get("maxReplicas") if spec.get("maxReplicas") is not None else 10),
        target_cpu_utilization_pct=int(spec.get("targetCPUUtilizationPercentage", 80)),
        current_replicas=int(status.get("currentReplicas", 0)),
        desired_replicas=int(status.get("desiredReplicas", 0)),
        current_cpu_utilization_pct=status.get("currentCPUUtilizationPercentage"),
        last_scale_time=_parse_time(status.get("lastScaleTime")),
    )


def hpa_to_k8s(hpa: HorizontalPodAutoscaler) -> dict:
    meta: Dict[str, Any] = {"name": hpa.name, "namespace": hpa.namespace}
    if hpa.resource_version:
        meta["resourceVersion"] = hpa.resource_version
    status: Dict[str, Any] = {
        "currentReplicas": hpa.current_replicas,
        "desiredReplicas": hpa.desired_replicas,
    }
    if hpa.current_cpu_utilization_pct is not None:
        status["currentCPUUtilizationPercentage"] = hpa.current_cpu_utilization_pct
    if hpa.last_scale_time is not None:
        status["lastScaleTime"] = _format_time(hpa.last_scale_time)
    return {
        "apiVersion": "autoscaling/v1",
        "kind": "HorizontalPodAutoscaler",
        "metadata": meta,
        "spec": {
            "scaleTargetRef": {"kind": hpa.target_kind, "name": hpa.target_name,
                               "apiVersion": "apps/v1"},
            "minReplicas": hpa.min_replicas,
            "maxReplicas": hpa.max_replicas,
            "targetCPUUtilizationPercentage": hpa.target_cpu_utilization_pct,
        },
        "status": status,
    }


@dataclass
class PodMetrics:
    """metrics.k8s.io PodMetrics — aggregate usage for one pod, published
    by the node runtime (hollow kubelets synthesize it); read by the HPA
    controller and `kubectl top pods`."""

    name: str = ""
    namespace: str = "default"
    resource_version: str = ""
    cpu_milli: int = 0
    memory_bytes: int = 0
    window_s: float = 30.0
    timestamp: float = 0.0

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


def _window_s(v) -> float:
    if isinstance(v, str):
        return float(v.rstrip("s") or 30)
    return float(v or 30)


def podmetrics_from_k8s(obj: dict) -> PodMetrics:
    meta = obj.get("metadata") or {}
    usage: Dict[str, int] = {}
    for c in obj.get("containers") or []:
        for k, v in (c.get("usage") or {}).items():
            usage[k] = usage.get(k, 0) + _request_value(k, parse_quantity(str(v)))
    return PodMetrics(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        resource_version=str(meta.get("resourceVersion", "")),
        cpu_milli=usage.get(RESOURCE_CPU, 0),
        memory_bytes=usage.get(RESOURCE_MEMORY, 0),
        window_s=_window_s(obj.get("window")),
        timestamp=_parse_time(obj.get("timestamp")) or 0.0,
    )


def podmetrics_to_k8s(pm: PodMetrics) -> dict:
    meta: Dict[str, Any] = {"name": pm.name, "namespace": pm.namespace}
    if pm.resource_version:
        meta["resourceVersion"] = pm.resource_version
    return {
        "apiVersion": "metrics.k8s.io/v1beta1",
        "kind": "PodMetrics",
        "metadata": meta,
        "timestamp": _format_time(pm.timestamp) if pm.timestamp else None,
        "window": f"{pm.window_s:g}s",
        "containers": [{
            "name": "total",
            "usage": {"cpu": f"{pm.cpu_milli}m", "memory": str(pm.memory_bytes)},
        }],
    }


@dataclass
class NodeMetrics:
    """metrics.k8s.io NodeMetrics — node aggregate usage for
    `kubectl top nodes`. Cluster-scoped (key = node name)."""

    name: str = ""
    resource_version: str = ""
    cpu_milli: int = 0
    memory_bytes: int = 0
    window_s: float = 30.0
    timestamp: float = 0.0

    def key(self) -> str:
        return self.name


def nodemetrics_from_k8s(obj: dict) -> NodeMetrics:
    meta = obj.get("metadata") or {}
    usage = obj.get("usage") or {}
    return NodeMetrics(
        name=meta.get("name", ""),
        resource_version=str(meta.get("resourceVersion", "")),
        cpu_milli=_request_value(RESOURCE_CPU, parse_quantity(str(usage.get("cpu", "0")))),
        memory_bytes=_request_value(RESOURCE_MEMORY, parse_quantity(str(usage.get("memory", "0")))),
        window_s=_window_s(obj.get("window")),
        timestamp=_parse_time(obj.get("timestamp")) or 0.0,
    )


def nodemetrics_to_k8s(nm: NodeMetrics) -> dict:
    meta: Dict[str, Any] = {"name": nm.name}
    if nm.resource_version:
        meta["resourceVersion"] = nm.resource_version
    return {
        "apiVersion": "metrics.k8s.io/v1beta1",
        "kind": "NodeMetrics",
        "metadata": meta,
        "timestamp": _format_time(nm.timestamp) if nm.timestamp else None,
        "window": f"{nm.window_s:g}s",
        "usage": {"cpu": f"{nm.cpu_milli}m", "memory": str(nm.memory_bytes)},
    }


def node_to_k8s(node: Node) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": node.name,
            "uid": node.uid,
            "labels": dict(node.labels),
            "annotations": dict(node.annotations),
            "resourceVersion": node.resource_version,
        },
        "spec": {
            **({"unschedulable": True} if node.unschedulable else {}),
            **(
                {
                    "taints": [
                        {"key": t.key, "value": t.value, "effect": t.effect} for t in node.taints
                    ]
                }
                if node.taints
                else {}
            ),
        },
        "status": {
            "capacity": {k: _quantity_str(k, v) for k, v in node.capacity.items()},
            "allocatable": {k: _quantity_str(k, v) for k, v in node.allocatable.items()},
            "images": [{"names": list(i.names), "sizeBytes": i.size_bytes} for i in node.images],
            "conditions": list(node.conditions),
        },
    }
