"""Kubernetes resource.Quantity arithmetic.

Re-implements the subset of `k8s.io/apimachinery/pkg/api/resource` the
scheduler depends on (reference: staging/src/k8s.io/apimachinery/pkg/api/resource/quantity.go):
parsing of decimal/binary-SI suffixed strings and the two accessors the
scheduler hot path uses — `Value()` (ceil to integer units, used for memory
bytes) and `MilliValue()` (ceil to 1/1000 units, used for CPU millicores).

Values are held exactly as integer-scaled decimals (mantissa x 10^exp or
mantissa x 2^exp for binary suffixes), so round-tripping and comparisons are
exact like the reference's inf.Dec-backed implementation.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction

_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
}
_BINARY_SUFFIXES = {
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}


class QuantityError(ValueError):
    pass


@dataclass(frozen=True)
class Quantity:
    """An exact decimal quantity of a resource."""

    value_exact: Fraction

    @staticmethod
    def parse(s: "str | int | float | Quantity") -> "Quantity":
        if isinstance(s, Quantity):
            return s
        if isinstance(s, int):
            return Quantity(Fraction(s))
        if isinstance(s, float):
            return Quantity(Fraction(str(s)))
        text = s.strip()
        if not text:
            raise QuantityError("empty quantity")
        # Split the numeric part from the suffix.
        i = 0
        if text[i] in "+-":
            i += 1
        seen_digit = False
        while i < len(text) and (text[i].isdigit() or text[i] == "."):
            if text[i].isdigit():
                seen_digit = True
            i += 1
        num, suffix = text[:i], text[i:]
        if not seen_digit:
            raise QuantityError(f"invalid quantity {s!r}")
        if suffix in _BINARY_SUFFIXES:
            mult = _BINARY_SUFFIXES[suffix]
        elif suffix in _DECIMAL_SUFFIXES:
            mult = _DECIMAL_SUFFIXES[suffix]
        elif suffix.startswith(("e", "E")) and suffix[1:].lstrip("+-").isdigit():
            mult = Fraction(10) ** int(suffix[1:])
        else:
            raise QuantityError(f"invalid quantity suffix {suffix!r} in {s!r}")
        try:
            base = Fraction(num)
        except (ValueError, ZeroDivisionError) as e:
            raise QuantityError(f"invalid quantity {s!r}") from e
        return Quantity(base * mult)

    def value(self) -> int:
        """Integer units, rounded up (Quantity.Value semantics). Memoized:
        the Fraction ceil sits on the oracle/encode hot paths and the
        dataclass is frozen, so the result can never change."""
        v = getattr(self, "_value_int", None)
        if v is None:
            ve = self.value_exact
            v = -((-ve.numerator) // ve.denominator)  # ceil, matches Go rounding up
            object.__setattr__(self, "_value_int", v)
        return v

    def milli_value(self) -> int:
        """1/1000 units, rounded up (Quantity.MilliValue semantics).
        Ceil straight off numerator/denominator: building the intermediate
        `value_exact * 1000` Fraction (gcd + coprime normalization) was the
        single hottest line of the whole commit loop at 4096-pod batches."""
        v = getattr(self, "_milli_int", None)
        if v is None:
            ve = self.value_exact
            v = -((-ve.numerator * 1000) // ve.denominator)
            object.__setattr__(self, "_milli_int", v)
        return v

    def is_zero(self) -> bool:
        return self.value_exact == 0

    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.value_exact + other.value_exact)

    def __lt__(self, other: "Quantity") -> bool:
        return self.value_exact < other.value_exact

    def __repr__(self) -> str:
        return f"Quantity({str(self.value_exact)})"


def parse_quantity(s) -> Quantity:
    return Quantity.parse(s)
