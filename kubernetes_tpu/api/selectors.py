"""Label and node-selector matching semantics (host-side / oracle).

Implements the exact matching rules the scheduler relies on:
- labels.SelectorFromSet / metav1.LabelSelectorAsSelector
  (staging/src/k8s.io/apimachinery/pkg/labels, .../apis/meta/v1/helpers.go)
- v1helper.MatchNodeSelectorTerms (pkg/apis/core/v1/helper/helpers.go), as
  called from predicates.go:925 nodeMatchesNodeSelectorTerms.

These are the single source of truth for string-world matching; the device
kernels operate on interned ids compiled from the same structures and are
parity-tested against these functions.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .types import (
    LabelSelector,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
)


def match_label_selector(selector: Optional[LabelSelector], labels: Dict[str, str]) -> bool:
    """metav1.LabelSelectorAsSelector: nil selector matches nothing; empty
    selector matches everything; matchLabels AND matchExpressions all must hold."""
    if selector is None:
        return False
    for k, v in selector.match_labels.items():
        if labels.get(k) != v:
            return False
    for req in selector.match_expressions:
        if not _match_label_requirement(req.key, req.operator, req.values, labels):
            return False
    return True


def _match_label_requirement(key: str, op: str, values: List[str], labels: Dict[str, str]) -> bool:
    present = key in labels
    if op == "In":
        return present and labels[key] in values
    if op == "NotIn":
        # labels.Requirement: NotIn is satisfied when the key is absent OR the
        # value is not in the list.
        return not present or labels[key] not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    raise ValueError(f"invalid label selector operator {op!r}")


def match_node_selector_requirement(req: NodeSelectorRequirement, labels: Dict[str, str]) -> bool:
    """nodeSelectorRequirementsAsSelector semantics, incl. Gt/Lt which parse
    the node label value as an integer (labels.Requirement ParseInt64)."""
    present = req.key in labels
    op = req.operator
    if op == "In":
        return present and labels[req.key] in req.values
    if op == "NotIn":
        return not present or labels[req.key] not in req.values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    if op in ("Gt", "Lt"):
        if not present or len(req.values) != 1:
            return False
        try:
            lbl = int(labels[req.key])
            val = int(req.values[0])
        except ValueError:
            return False
        return lbl > val if op == "Gt" else lbl < val
    raise ValueError(f"invalid node selector operator {op!r}")


def match_node_selector_term(
    term: NodeSelectorTerm, labels: Dict[str, str], fields: Optional[Dict[str, str]] = None
) -> bool:
    """A term with no (nil/empty) requirements matches nothing
    (predicates.go:959-966 commentary); matchExpressions AND matchFields."""
    if not term.match_expressions and not term.match_fields:
        return False
    for req in term.match_expressions:
        if not match_node_selector_requirement(req, labels):
            return False
    for req in term.match_fields:
        # NodeSelectorRequirementsAsFieldSelector (pkg/apis/core/v1/helper/helpers.go)
        # supports only In/NotIn with exactly one value; any other op or
        # cardinality is a conversion error, which makes the term match nothing.
        if req.operator not in ("In", "NotIn") or len(req.values) != 1:
            return False
        if not match_node_selector_requirement(req, fields or {}):
            return False
    return True


def match_node_selector_terms(
    terms: List[NodeSelectorTerm], labels: Dict[str, str], fields: Optional[Dict[str, str]] = None
) -> bool:
    """Terms are ORed; an empty list matches nothing (predicates.go:922)."""
    return any(match_node_selector_term(t, labels, fields) for t in terms)


def node_matches_node_selector(ns: Optional[NodeSelector], node_labels: Dict[str, str], node_name: str = "") -> bool:
    if ns is None:
        return True
    fields = {"metadata.name": node_name} if node_name else {}
    return match_node_selector_terms(ns.node_selector_terms, node_labels, fields)
